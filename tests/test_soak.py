"""Chaos soak subsystem (ISSUE 11): ChurnScript determinism, watch-intake
backpressure, ordered shutdown, crash-restart re-adoption, HA failover under
churn, and the scaled end-to-end soak.

Fast tests run tier-1; everything spawning operator processes is
slow-marked (like the bench regression gate) so tier-1 stays quick."""

import gzip
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from karpenter_tpu.api import ObjectMeta, Pod, Provisioner, Resources
from karpenter_tpu.api.codec import KINDS, to_wire
from karpenter_tpu.cloudprovider import generate_catalog
from karpenter_tpu.cloudprovider.httpcloud import CloudHTTPService
from karpenter_tpu.soak import ChurnEvent, ChurnScript, InvariantMonitor
from karpenter_tpu.soak.monitor import memory_slope_bps, parse_metrics
from karpenter_tpu.state import Cluster, ClusterAPIServer, HTTPCluster
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.faults import FaultPlan

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _wait(predicate, timeout, step=0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(step)
    return False


# ---------------------------------------------------------------------------
# ChurnScript: the unified timeline DSL (satellite: single seeded RNG +
# injected clock across FaultPlan / InterruptionSchedule / the harness)
# ---------------------------------------------------------------------------

class TestChurnScript:
    def test_identical_seed_reproduces_identical_timeline(self):
        a = ChurnScript.generate(seed=42, duration_s=20, rate_hz=300)
        b = ChurnScript.generate(seed=42, duration_s=20, rate_hz=300)
        assert a.events == b.events
        assert a.total_weight() == b.total_weight()

    def test_different_seed_differs(self):
        a = ChurnScript.generate(seed=1, duration_s=20, rate_hz=300)
        b = ChurnScript.generate(seed=2, duration_s=20, rate_hz=300)
        assert a.events != b.events

    def test_generate_includes_required_chaos(self):
        s = ChurnScript.generate(
            seed=3, duration_s=30, rate_hz=200,
            operator_restarts=((0.4, "kill"),), apiserver_restarts=(0.7,),
        )
        kinds = {e.kind for e in s.events}
        assert "operator-restart" in kinds and "apiserver-restart" in kinds
        assert any(e.kind == "reclaim-wave" for e in s.events)
        assert any(e.kind == "ice-start" for e in s.events)
        # weight approximates the rate target: pod churn dominates
        assert s.total_weight() >= 30 * 200 * 0.8

    def test_due_yields_in_order_exactly_once(self):
        s = ChurnScript.generate(seed=5, duration_s=10, rate_hz=100)
        first = list(s.due(now=4.0))
        assert first and all(e.t <= 4.0 for e in first)
        assert [e.t for e in first] == sorted(e.t for e in first)
        assert not list(s.due(now=4.0))  # exactly once
        rest = list(s.due(now=10.1))
        assert all(e.t > 4.0 for e in rest)
        assert len(first) + len(rest) == len(s.events)
        assert s.pending() == 0

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            ChurnEvent(t=0.0, kind="meteor-strike")

    def test_builder_api(self):
        s = ChurnScript(seed=9)
        s.at(1.0).deploy_up("a", 5)
        s.at(2.0).ice(("*", "zone-a", "spot"), duration_s=3.0)
        s.at(4.0).operator_restart(signal="term")
        kinds = [e.kind for e in s.events]
        assert kinds == ["deploy-up", "ice-start", "operator-restart", "ice-end"]
        assert s.events[0].weight == 5

    def test_interruption_schedule_projection_shares_clock(self):
        s = ChurnScript(seed=1)
        s.at(2.5).reclaim_wave(pool=("*", "zone-b", "spot"), fraction=0.5)
        s.at(7.0).price_spike(zone="zone-a", factor=3.0)
        sched = s.interruption_schedule(round_s=1.0)
        assert [w.round_no for w in sched.waves] == [2]
        assert sched.waves[0].pool == ("*", "zone-b", "spot")
        assert [p.round_no for p in sched.spikes] == [7]
        # same injected clock: fired events stamp the script's time axis
        # (bound-method equality: same receiver, same function)
        assert sched.clock == s.elapsed

    def test_faultplan_shares_script_clock(self):
        times = iter([10.0, 20.0, 25.0])
        s = ChurnScript(seed=1, clock=lambda: next(times))
        s.start()  # t0 = 10.0
        s.faults.fail("/v1/run-instances", n=2)
        assert s.faults.next("/v1/run-instances") is not None
        assert s.faults.next("/v1/run-instances") is not None
        assert [round(t, 3) for t, _, _ in s.faults.timeline] == [10.0, 15.0]


# ---------------------------------------------------------------------------
# InvariantMonitor: leak detector + metrics parsing + verdicts
# ---------------------------------------------------------------------------

class TestInvariantMonitor:
    def test_memory_slope_detects_linear_leak(self):
        start = 100.0
        samples = [(float(t), start, 1e8 + t * 500_000.0) for t in range(60)]
        slope, segments = memory_slope_bps(samples)
        assert segments == 1
        assert 400_000 < slope < 600_000

    def test_memory_slope_flat_is_zero(self):
        samples = [(float(t), 1.0, 1e8 + (t % 2) * 1000) for t in range(60)]
        slope, segments = memory_slope_bps(samples)
        assert segments == 1
        assert abs(slope) < 1000

    def test_restart_rss_reset_not_a_negative_leak(self):
        # incarnation 1 at high RSS, incarnation 2 restarts low and stays
        # flat: an unsegmented regression would see a huge negative (or,
        # reversed, positive) slope across the reset
        s1 = [(float(t), 1.0, 5e8) for t in range(80)]
        s2 = [(80.0 + t, 2.0, 1e8) for t in range(80)]
        slope, segments = memory_slope_bps(s1 + s2)
        assert segments == 2
        assert abs(slope) < 1000

    def test_short_post_restart_segment_skipped(self):
        # 20 s of steeply-climbing warmup right after a restart must not
        # read as a leak — below the warmup + min qualifying span it is
        # boot ramp, not a trend
        s1 = [(float(t), 1.0, 1e8) for t in range(80)]
        s2 = [(80.0 + t, 2.0, 1e8 + t * 5e6) for t in range(20)]
        slope, segments = memory_slope_bps(s1 + s2)
        assert segments == 1
        assert abs(slope) < 1000

    def test_parse_metrics(self):
        text = (
            "# HELP x y\n# TYPE x gauge\n"
            'x{controller="gc"} 1.5\n'
            "karpenter_tpu_process_memory_bytes 123456\n"
            "bad line\n"
        )
        out = parse_metrics(text)
        assert ("x", {"controller": "gc"}, 1.5) in out
        assert ("karpenter_tpu_process_memory_bytes", {}, 123456.0) in out

    def test_report_flags_each_invariant(self):
        mon = InvariantMonitor(ready_p99_budget_s=1.0, loop_lag_budget_s=1.0,
                               mem_slope_budget_bps=100.0)
        mon.ready_latencies = [5.0] * 10
        mon.loop_lag_max_s = 9.0
        mon.mem_samples = [(float(t), 1.0, 1e8 + t * 1e6) for t in range(60)]
        report = mon.report(
            pending_end=3,
            launch_audit={"duplicate_tokens": {"tok": ["i-1", "i-2"]}},
            orphan_instances=["i-9"],
            replay={"found": 1, "mismatched": ["c1"], "errors": []},
        )
        text = "\n".join(report["violations"])
        assert not report["ok"]
        for needle in ("p99", "loop lag", "memory slope", "pending",
                       "duplicate", "orphaned", "diverged"):
            assert needle in text, f"missing violation for {needle}"

    def test_report_clean(self):
        mon = InvariantMonitor()
        mon.ready_latencies = [0.1] * 50
        report = mon.report(pending_end=0, launch_audit={}, orphan_instances=[])
        assert report["ok"] and report["violations"] == []


class TestBusyBoxProbe:
    """The bench soak arm's pre-flight contention probe (ISSUE 14): a
    loaded box must degrade the arm to an EXPLICIT skip with a reason —
    never a false invariant failure — and the skip shape must carry the
    gate-facing fields as nulls so the summary line stays parseable."""

    def test_busy_box_degrades_to_explicit_skip(self, monkeypatch):
        import bench

        monkeypatch.setattr(
            bench, "_box_busy_probe", lambda **kw: "synthetic: box busy"
        )
        out = bench.bench_soak(duration_s=1.0)
        assert out["skipped_busy_box"] is True
        assert "busy" in out["reason"]
        assert out["invariant_violations"] == 0
        assert out["events_per_s"] is None

    def test_probe_decided_by_spin_arm_not_loadavg(self, monkeypatch):
        """Load average is context, not the decider: a decaying loadavg
        from a just-finished run (idle box, spin clean) must NOT skip the
        soak — only active time-slicing does."""
        import os

        import bench

        monkeypatch.setattr(os, "getloadavg", lambda: (99.0, 99.0, 99.0))
        assert bench._box_busy_probe(spin_ratio=1e9) is None


# ---------------------------------------------------------------------------
# Watch-intake backpressure (HTTPCluster bounded queue)
# ---------------------------------------------------------------------------

class TestBackpressure:
    def test_widen_coalesces_to_newest_per_object(self):
        api = ClusterAPIServer().start()
        try:
            client = HTTPCluster(api.endpoint, watch=False, queue_capacity=64)
            client._widened = True
            base = metrics.BACKPRESSURE_EVENTS.value({"action": "widen"})
            pod = Pod(meta=ObjectMeta(name="w-1"),
                      requests=Resources(cpu="100m", memory="64Mi"))
            wires = []
            for v in (5, 6, 7):
                pod.meta.resource_version = v
                wires.append({"resourceVersion": v, "event": "MODIFIED",
                              "kind": "pods", "object": to_wire(pod)})
            client._apply_events(wires)
            # two superseded intermediates coalesced away; newest applied
            assert metrics.BACKPRESSURE_EVENTS.value({"action": "widen"}) - base == 2
            assert client.pods["w-1"].meta.resource_version == 7
            client.close()
        finally:
            api.stop()

    def test_overflow_sheds_and_relists(self):
        api = ClusterAPIServer().start()
        writer = HTTPCluster(api.endpoint, watch=False)
        client = HTTPCluster(api.endpoint, queue_capacity=8)
        try:
            base = metrics.BACKPRESSURE_EVENTS.value({"action": "shed"})
            # hold the applier so fetched events pile into the bounded queue
            with client.quiesce():
                for i in range(40):
                    writer.add_pod(Pod(
                        meta=ObjectMeta(name=f"shed-{i}"),
                        requests=Resources(cpu="50m", memory="32Mi"),
                    ))
                assert _wait(
                    lambda: metrics.BACKPRESSURE_EVENTS.value(
                        {"action": "shed"}) > base,
                    timeout=20,
                ), "intake overflow never shed"
            # after release the queued relist rebuilds the full cache
            assert _wait(lambda: len(client.pods) == 40, timeout=20), (
                f"cache never converged after shed: {len(client.pods)}"
            )
        finally:
            client.close()
            writer.close()
            api.stop()

    def test_quiesce_holds_remote_events_until_release(self):
        api = ClusterAPIServer().start()
        writer = HTTPCluster(api.endpoint, watch=False)
        client = HTTPCluster(api.endpoint)
        try:
            with client.quiesce():
                writer.add_pod(Pod(
                    meta=ObjectMeta(name="q-1"),
                    requests=Resources(cpu="50m", memory="32Mi"),
                ))
                time.sleep(1.0)  # ample time for fetch; apply must NOT run
                assert "q-1" not in client.pods
            assert _wait(lambda: "q-1" in client.pods, timeout=10)
        finally:
            client.close()
            writer.close()
            api.stop()

    def test_apiserver_listener_restart_forces_relist(self):
        """A fresh apiserver incarnation over the same backing store resets
        the event log; stale client bookmarks (AHEAD of the new log) must
        get 'gone' and relist, or the client cache wedges forever."""
        backing = Cluster()
        api = ClusterAPIServer(backing=backing).start()
        port = api._server.server_address[1]
        client = HTTPCluster(api.endpoint)
        try:
            client.add_pod(Pod(meta=ObjectMeta(name="r-1"),
                               requests=Resources(cpu="50m", memory="32Mi")))
            assert _wait(lambda: client._bookmark >= 1, timeout=5)
            api.stop()
            api = ClusterAPIServer(backing=backing, port=port).start()
            # a write through the NEW incarnation (small seqs) must reach the
            # old client despite its large pre-restart bookmark
            backing.add_pod(Pod(meta=ObjectMeta(name="r-2"),
                                requests=Resources(cpu="50m", memory="32Mi")))
            assert _wait(lambda: "r-2" in client.pods, timeout=30), (
                "client never recovered from the apiserver restart"
            )
        finally:
            client.close()
            api.stop()


# ---------------------------------------------------------------------------
# Ordered shutdown + flight-recorder flush + launch audit
# ---------------------------------------------------------------------------

class TestShutdownOrdering:
    def test_close_releases_lease_and_flushes_before_port(self):
        from karpenter_tpu.operator import Operator

        order = []

        class FakeElector:
            def release(self):
                order.append("lease")

        class FakeServer:
            recorder = None

            def stop(self):
                order.append("port")

        op = Operator.new()
        op.elector = FakeElector()
        op.http_server = FakeServer()
        op.close()
        assert order == ["lease", "port"], order

    def test_close_port_released_even_when_steps_fail(self):
        from karpenter_tpu.operator import Operator

        stopped = []

        class ExplodingElector:
            def release(self):
                raise RuntimeError("lease storage gone")

        class FakeServer:
            recorder = None

            def stop(self):
                stopped.append(True)

        op = Operator.new()
        op.elector = ExplodingElector()
        op.http_server = FakeServer()
        op.close()  # must not raise
        assert stopped == [True]

    def test_flush_dumps_writes_missed_anomaly_capsules(self, tmp_path):
        from karpenter_tpu.utils.flightrecorder import FlightRecorder

        rec = FlightRecorder(capacity=8)  # no dump dir yet: auto-dump misses
        rec._commit({"id": "a1", "controller": "provisioning",
                     "anomalies": ["unschedulable-pods"], "inputs": {},
                     "outputs": {}}, ["unschedulable-pods"])
        rec._commit({"id": "ok1", "controller": "provisioning",
                     "anomalies": [], "inputs": {}, "outputs": {}}, [])
        rec.dump_dir = str(tmp_path)
        written = rec.flush_dumps()
        assert len(written) == 1 and "a1" in written[0]
        assert rec.flush_dumps() == []  # idempotent: already on disk
        with gzip.open(written[0]) as f:
            assert json.load(f)["id"] == "a1"

    def test_launch_audit_flags_duplicate_tokens(self):
        svc = CloudHTTPService(catalog=generate_catalog(n_types=4))
        svc.launch_log = [("t1", "i-1", 0.0), ("t1", "i-2", 1.0),
                          ("t2", "i-3", 2.0), ("", "i-4", 3.0)]
        audit = svc.launch_audit()
        assert audit["duplicate_tokens"] == {"t1": ["i-1", "i-2"]}
        assert audit["tokens"] == 2 and audit["untokened"] == 1

    def test_machine_name_seq_seeded_past_existing(self):
        from karpenter_tpu.controllers.provisioning import (
            MachineNameSeq,
            seed_machine_names,
        )

        cluster = Cluster()
        cluster.add_provisioner(Provisioner(meta=ObjectMeta(name="default")))
        from karpenter_tpu.api.objects import Machine, Node

        cluster.add_machine(Machine(meta=ObjectMeta(name="default-7"),
                                    provisioner_name="default"))
        cluster.add_node(Node(meta=ObjectMeta(name="default-12")))
        seq = MachineNameSeq()
        assert seed_machine_names(cluster, seq) == 12
        assert seq.next() == 13


# ---------------------------------------------------------------------------
# Slow: process-level chaos (operator subprocesses)
# ---------------------------------------------------------------------------

def _operator_env(dump_dir, extra=None):
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["KARPENTER_TPU_FLIGHT_RECORDER_DUMP_DIR"] = str(dump_dir)
    env["KARPENTER_TPU_GARBAGE_COLLECT_INTERVAL"] = "2"
    env.update(extra or {})
    return env


def _spawn_operator(api, cloud, port, log_path, env):
    log = open(log_path, "w")
    return subprocess.Popen(
        [sys.executable, "-m", "karpenter_tpu",
         "--cluster-endpoint", api.endpoint,
         "--cloud-endpoint", cloud.endpoint,
         "--metrics-port", str(port), "--metrics-bind", "127.0.0.1",
         "--batch-idle-duration", "0.1", "--batch-max-duration", "0.5",
         "--tick", "0.05"],
        cwd=ROOT, env=env, stdout=log, stderr=subprocess.STDOUT, text=True,
    )


def _kill(procs):
    for p in procs:
        if p and p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        if p:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()


def _http_json(url, timeout=3.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _clone_cluster(backing):
    """Wire-faithful copy of a backing store (the crash-restart digest
    control starts a never-crashed operator against an identical state)."""
    clone = Cluster()
    with backing._lock:
        snap = {
            kind: [KINDS[kind][1](o) for o in getattr(backing, attr).values()]
            for kind, attr in (
                ("provisioners", "provisioners"), ("nodetemplates", "node_templates"),
                ("poddisruptionbudgets", "pdbs"), ("nodes", "nodes"),
                ("machines", "machines"), ("pods", "pods"),
            )
        }
        version = backing._version
    for kind, wires in snap.items():
        decode = KINDS[kind][2]
        coll = {
            "provisioners": clone.provisioners, "nodetemplates": clone.node_templates,
            "poddisruptionbudgets": clone.pdbs, "nodes": clone.nodes,
            "machines": clone.machines, "pods": clone.pods,
        }[kind]
        for w in wires:
            obj = decode(w)
            coll[obj.meta.name] = obj
    clone._version = version
    return clone


def _free_port():
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
class TestCrashRestartReadoption:
    def test_kill_midflight_then_restart_matches_control(self, tmp_path):
        """Satellite: SIGKILL an operator holding bound pods, an in-flight
        launch and a node mid-deletion; the restarted operator must resume
        termination, adopt/collect the orphaned instance, launch no
        duplicates, and its first solve digest must equal a never-crashed
        control operator's over an identical cluster copy."""
        plan = FaultPlan()
        cloud = CloudHTTPService(
            catalog=generate_catalog(n_types=12), fault_plan=plan
        ).start()
        api = ClusterAPIServer().start()
        client = HTTPCluster(api.endpoint)
        port_a, port_b, port_c = _free_port(), _free_port(), _free_port()
        a = b = c = None
        cloud2 = api2 = None
        try:
            client.add_provisioner(Provisioner(meta=ObjectMeta(name="default")))
            a = _spawn_operator(api, cloud, port_a, tmp_path / "op-a.log",
                                _operator_env(tmp_path / "caps-a"))
            for i in range(6):
                client.add_pod(Pod(
                    meta=ObjectMeta(name=f"base-{i}", owner_kind="ReplicaSet"),
                    requests=Resources(cpu="250m", memory="256Mi"),
                ))
            assert _wait(
                lambda: all(p.node_name for p in client.pods.values())
                and len(client.pods) == 6,
                timeout=120,
            ), "baseline pods never bound"

            # node mid-deletion: terminate blocked so the finalizer parks
            plan.fail("/v1/terminate", n=100, status=503)
            victim = sorted(client.nodes)[0]
            node = client.nodes[victim]
            node.meta.deletion_timestamp = time.time()
            client.update(node)
            assert _wait(
                lambda: client.nodes.get(victim) is not None
                and client.nodes[victim].unschedulable,
                timeout=60,
            ), "victim never cordoned (termination not running?)"

            # in-flight launch: create hangs server-side; kill mid-flight
            calls0 = cloud.request_log.count("/v1/run-instances")
            plan.latency("/v1/run-instances", seconds=6.0, n=1)
            client.add_pod(Pod(
                meta=ObjectMeta(name="midflight-0", owner_kind="ReplicaSet"),
                requests=Resources(cpu="250m", memory="256Mi"),
            ))
            assert _wait(
                lambda: cloud.request_log.count("/v1/run-instances") > calls0,
                timeout=60,
            ), "launch never reached the cloud"
            a.kill()  # SIGKILL: no ordered shutdown, that's the point
            a.wait(timeout=15)
            instances0 = len(cloud.instances)
            # the server-side launch completes after the client died: an
            # instance with no Machine — the orphan GC must handle
            assert _wait(lambda: len(cloud.instances) > instances0, timeout=30)
            plan.clear("/v1/terminate")

            # copy the quiescent store for the never-crashed control
            clone = _clone_cluster(api.backing)
            api2 = ClusterAPIServer(backing=clone).start()
            cloud2 = CloudHTTPService(catalog=generate_catalog(n_types=12)).start()

            b = _spawn_operator(api, cloud, port_b, tmp_path / "op-b.log",
                                _operator_env(tmp_path / "caps-b"))
            c = _spawn_operator(api2, cloud2, port_c, tmp_path / "op-c.log",
                                _operator_env(tmp_path / "caps-c"))

            # recovery: pending pod binds, mid-deletion node finishes dying
            assert _wait(
                lambda: (p := client.pods.get("midflight-0")) is not None
                and p.node_name is not None,
                timeout=180,
            ), "restarted operator never placed the midflight pod"
            assert _wait(
                lambda: victim not in client.nodes, timeout=120,
            ), "termination never resumed on the mid-deletion node"

            # no orphans: every live instance referenced by a machine
            def orphans():
                known = {
                    m.status.provider_id.rsplit("/", 1)[-1]
                    for m in api.backing.machines.values()
                    if m.status.provider_id
                }
                return [i for i in cloud.instances if i not in known]

            assert _wait(lambda: not orphans(), timeout=90), (
                f"orphaned instances never adopted/collected: {orphans()}"
            )
            # no duplicate machines / no duplicate launches
            audit = cloud.launch_audit()
            assert audit["duplicate_tokens"] == {}
            pids = [m.status.provider_id for m in api.backing.machines.values()
                    if m.status.provider_id]
            assert len(pids) == len(set(pids)), f"duplicate provider ids: {pids}"

            # digest control: B's first provisioning capsule vs C's
            def first_prov_digests(port):
                caps = _http_json(
                    f"http://127.0.0.1:{port}/debug/flightrecorder"
                )["capsules"]
                prov = [x for x in caps if x["controller"] == "provisioning"]
                if not prov:
                    return None
                oldest = prov[-1]["id"]  # list is newest-first
                raw = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/debug/flightrecorder/{oldest}",
                    timeout=5,
                ).read()
                capsule = json.loads(gzip.decompress(raw))
                return capsule["outputs"]["problem_digests"]

            assert _wait(lambda: first_prov_digests(port_b) is not None, timeout=60)
            assert _wait(lambda: first_prov_digests(port_c) is not None, timeout=60)
            db, dc = first_prov_digests(port_b), first_prov_digests(port_c)
            assert db == dc and db, (
                f"restarted operator's first solve diverged from the "
                f"never-crashed control: {db} vs {dc}"
            )
        finally:
            _kill([a, b, c])
            client.close()
            api.stop()
            cloud.stop()
            if api2 is not None:
                api2.stop()
            if cloud2 is not None:
                cloud2.stop()


@pytest.mark.slow
class TestHAFailoverMidChurn:
    def test_leader_killed_mid_churn_no_duplicate_launches(self, tmp_path):
        """Satellite: settings-driven leader election (two operators, one
        apiserver), leader SIGKILLed while pods stream in; the standby takes
        over within the lease TTL and the client-token audit shows zero
        duplicate launches across the failover."""
        lease = str(tmp_path / "lease")
        cloud = CloudHTTPService(catalog=generate_catalog(n_types=12)).start()
        api = ClusterAPIServer().start()
        client = HTTPCluster(api.endpoint)
        ports = (_free_port(), _free_port())
        env = _operator_env(tmp_path, extra={
            # the SETTINGS path, not the CLI flag — exercises the satellite
            "KARPENTER_TPU_LEADER_ELECTION_ENABLED": "true",
            "KARPENTER_TPU_LEADER_ELECTION_LEASE_PATH": lease,
        })
        procs = []
        try:
            client.add_provisioner(Provisioner(meta=ObjectMeta(name="default")))
            procs = [
                _spawn_operator(api, cloud, p, tmp_path / f"ha-{p}.log", env)
                for p in ports
            ]

            def leader_states():
                out = []
                for p in ports:
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{p}/leaderz", timeout=2
                        ) as r:
                            out.append(r.status == 200)
                    except Exception:
                        out.append(False)
                return out

            assert _wait(lambda: sum(leader_states()) == 1, timeout=120), (
                f"expected exactly one leader, got {leader_states()}"
            )
            leader = leader_states().index(True)

            # churn: pods stream in while we kill the leader mid-stream
            for i in range(10):
                client.add_pod(Pod(
                    meta=ObjectMeta(name=f"churn-a-{i}", owner_kind="ReplicaSet"),
                    requests=Resources(cpu="200m", memory="128Mi"),
                ))
                if i == 5:
                    procs[leader].kill()
                    procs[leader].wait(timeout=15)
                time.sleep(0.2)
            standby = 1 - leader
            assert _wait(lambda: leader_states()[standby], timeout=60), (
                "standby never took leadership within the lease TTL"
            )
            for i in range(5):
                client.add_pod(Pod(
                    meta=ObjectMeta(name=f"churn-b-{i}", owner_kind="ReplicaSet"),
                    requests=Resources(cpu="200m", memory="128Mi"),
                ))
            assert _wait(
                lambda: all(p.node_name for p in client.pods.values()),
                timeout=180,
            ), "pods never all bound after failover"

            audit = cloud.launch_audit()
            assert audit["duplicate_tokens"] == {}, audit
            pids = [m.status.provider_id for m in api.backing.machines.values()
                    if m.status.provider_id]
            assert len(pids) == len(set(pids)), f"duplicate machines: {pids}"
        finally:
            _kill(procs)
            client.close()
            api.stop()
            cloud.stop()


@pytest.mark.slow
class TestScaledSoak:
    def test_scaled_soak_end_to_end(self):
        """The acceptance scenario: >=60 s of sustained churn over the real
        HTTP stack including >=1 apiserver restart and >=1 operator
        SIGKILL+restart, zero invariant violations, and byte-identical
        offline replay of every dumped anomaly capsule."""
        from karpenter_tpu.soak import SoakConfig, run_soak

        report = run_soak(SoakConfig(
            duration_s=75.0,        # >=60 s criterion, with margin so the
            #                         post-kill incarnation's memory window
            #                         clears the leak detector's min-span
            rate_hz=0.0,            # box-calibrated, capped at the 1k/s
            rate_target_hz=1000.0,  # acceptance target (driver hardware)
            seed=11,
            operator_restarts=((0.25, "kill"),),
            apiserver_restarts=(0.6,),
        ))
        assert report["restarts"]["operator_kill"] >= 1
        assert report["restarts"]["apiserver"] >= 1
        assert report["duration_s"] >= 60.0
        # achieved churn must be meaningful relative to the calibrated
        # target (the absolute >=1k/s criterion is driver-class hardware)
        assert report["events_per_s"] >= max(100.0, 0.5 * report["rate_hz"])
        # the leak detector must have judged at least one qualifying window
        assert report["mem_segments"] >= 1
        replay = report["replay"]
        assert replay["mismatched"] == [] and replay["errors"] == [], replay
        assert report["ok"], f"invariants tripped: {report['violations']}"
