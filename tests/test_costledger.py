"""ISSUE 19 suite: the cost ledger — continuous spend metering from watch
events, conservation-checked attribution, counterfactual streams, the
``/debug/costs`` surface, and byte-identical capsule replay of the
per-round ledger delta (including the on-demand price counterfactual)."""

from __future__ import annotations

import json
import random
import types
import urllib.request

import pytest

from karpenter_tpu.api import ObjectMeta, Resources
from karpenter_tpu.api import labels as wk
from karpenter_tpu.api.objects import Node
from karpenter_tpu.api.settings import Settings
from karpenter_tpu.cloudprovider import FakeCloudProvider, generate_catalog
from karpenter_tpu.controllers.provisioning import ProvisioningController
from karpenter_tpu.replay import replay_capsule
from karpenter_tpu.solver.solver import GreedySolver
from karpenter_tpu.state import Cluster
from karpenter_tpu.utils import metrics
from karpenter_tpu.utils.cache import FakeClock
from karpenter_tpu.utils.costledger import (
    IDLE,
    NO_GANG,
    CostLedger,
    round_cost_delta,
)
from karpenter_tpu.utils.decisions import DECISIONS
from karpenter_tpu.utils.flightrecorder import FLIGHT
from karpenter_tpu.utils.httpserver import OperatorHTTPServer

from helpers import make_pod, make_pods, make_provisioner


@pytest.fixture(autouse=True)
def _fresh_rings():
    DECISIONS.configure(2048)
    DECISIONS.clear()
    FLIGHT.configure(32)
    FLIGHT.clear()
    yield
    FLIGHT.clear()
    DECISIONS.clear()


def make_node(name, instance_type, zone, capacity_type,
              provisioner="default", cpu="8", memory="32Gi"):
    return Node(
        meta=ObjectMeta(name=name, labels={
            wk.INSTANCE_TYPE: instance_type,
            wk.ZONE: zone,
            wk.CAPACITY_TYPE: capacity_type,
            wk.PROVISIONER_NAME: provisioner,
        }),
        provider_id=f"fake:///{zone}/i-{name}",
        capacity=Resources(cpu=cpu, memory=memory),
        allocatable=Resources(cpu=cpu, memory=memory),
        ready=True,
    )


def ledger_env(window_s=600.0, n_types=12):
    clock = FakeClock(0.0)
    provider = FakeCloudProvider(catalog=generate_catalog(n_types=n_types))
    cluster = Cluster()
    ledger = CostLedger(
        cluster, provider.pricing, clock=clock, window_s=window_s
    ).attach()
    return cluster, provider, ledger, clock


# ---------------------------------------------------------------------------
# Conservation property under random interleavings
# ---------------------------------------------------------------------------


class TestConservationProperty:
    @pytest.mark.parametrize("seed", [0, 1, 7, 2026])
    def test_random_interleavings_conserve_and_match_offline_integral(
        self, seed
    ):
        """Random launch/bind/unbind/terminate/reclaim/consolidation
        interleavings under a fake clock: (a) every partition sums to the
        metered total at every settle point (conservation), and (b) the
        ledger total equals an INDEPENDENT offline integration of each
        node's price over its lifespan (piecewise-constant rate, so the
        trapezoid rule is exact) — metering and integration must agree."""
        rng = random.Random(seed)
        cluster, provider, ledger, clock = ledger_env()
        open_t, price_of = {}, {}
        offline = 0.0  # closed-span dollars, integrated independently
        node_i = pod_i = 0
        live_pods = []

        def launch():
            nonlocal node_i
            it = rng.choice(provider.catalog)
            off = rng.choice(it.offerings)
            node_i += 1
            name = f"n{node_i}"
            cluster.add_node(make_node(name, it.name, off.zone, off.capacity_type))
            open_t[name] = clock.now()
            p = provider.pricing.price(it.name, off.zone, off.capacity_type)
            price_of[name] = float(p) if p is not None else 0.0

        def terminate():
            nonlocal offline
            if not open_t:
                return
            name = rng.choice(sorted(open_t))
            for pod in [
                p for p in cluster.pods.values() if p.node_name == name
            ]:
                cluster.delete_pod(pod.meta.name)
                if pod.meta.name in live_pods:
                    live_pods.remove(pod.meta.name)
            cluster.delete_node(name)
            offline += price_of.pop(name) * (clock.now() - open_t.pop(name)) / 3600.0

        def bind():
            nonlocal pod_i
            if not open_t:
                return
            pod_i += 1
            gang = rng.choice([None, "gang-a", "gang-b"])
            pod = make_pod(
                name=f"cl-p{pod_i}",
                cpu=rng.choice(["250m", "1", "4"]),
                memory=rng.choice(["512Mi", "2Gi"]),
                labels={wk.POD_GROUP: gang} if gang else None,
            )
            cluster.add_pod(pod)
            cluster.bind_pod(pod.meta.name, rng.choice(sorted(open_t)))
            live_pods.append(pod.meta.name)

        def unbind():
            if live_pods:
                cluster.delete_pod(live_pods.pop(rng.randrange(len(live_pods))))

        for step in range(300):
            clock.step(rng.uniform(0.0, 45.0))
            r = rng.random()
            if r < 0.30:
                launch()
            elif r < 0.45:
                terminate()
            elif r < 0.75:
                bind()
            elif r < 0.90:
                unbind()
            elif r < 0.95:
                ledger.note_reclaim(("t", "z", wk.CAPACITY_TYPE_SPOT))
            else:
                ledger.note_consolidation(
                    types.SimpleNamespace(savings=rng.uniform(0.01, 2.0))
                )
            if step % 25 == 0:
                ledger.settle()
                verdict = ledger.conservation()
                assert verdict["ok"], verdict

        while open_t:
            terminate()
        clock.step(5.0)
        t = ledger.settle()
        verdict = ledger.conservation()
        assert verdict["ok"], verdict
        # the independent integral: every span is now closed
        assert ledger.total_dollars == pytest.approx(
            offline, rel=1e-9, abs=1e-9
        )
        assert ledger.total_dollars > 0.0  # 300 steps cannot be a no-op run
        # spot counterfactual: on-demand sticker is never below realized
        assert ledger.ondemand_dollars >= ledger.total_dollars - 1e-9
        assert ledger.savings_spot >= -1e-9

    def test_dominant_share_attribution_and_exact_idle_remainder(self):
        cluster, provider, ledger, clock = ledger_env()
        it = provider.catalog[0]
        off = it.offerings[0]
        cluster.add_node(make_node("n1", it.name, off.zone, off.capacity_type,
                                   cpu="8", memory="32Gi"))
        # dominant share 0.5 (4/8 cpu beats 8/32 memory)
        pod = make_pod(name="cl-half", cpu="4", memory="8Gi",
                       labels={wk.POD_GROUP: "gang-x"})
        cluster.add_pod(pod)
        cluster.bind_pod("cl-half", "n1")
        clock.step(3600.0)
        ledger.settle()
        price = float(provider.pricing.price(it.name, off.zone, off.capacity_type))
        assert ledger.total_dollars == pytest.approx(price)
        assert ledger.by_gang["gang-x"] == pytest.approx(price * 0.5)
        # idle is the EXACT remainder, not an independently-computed share
        assert ledger.by_gang[IDLE] == (
            ledger.total_dollars - ledger.by_gang["gang-x"]
        )
        assert ledger.by_pod["cl-half"]["dollars"] == pytest.approx(price * 0.5)
        assert ledger.conservation()["ok"]

    def test_oversubscribed_residents_normalize_with_no_idle(self):
        cluster, provider, ledger, clock = ledger_env()
        it = provider.catalog[0]
        off = it.offerings[0]
        cluster.add_node(make_node("n1", it.name, off.zone, off.capacity_type,
                                   cpu="4", memory="16Gi"))
        for i in range(3):  # 3 × 3/4 cpu → Σ shares 2.25, normalized to 1.0
            p = make_pod(name=f"cl-big{i}", cpu="3", memory="1Gi")
            cluster.add_pod(p)
            cluster.bind_pod(p.meta.name, "n1")
        clock.step(1800.0)
        ledger.settle()
        assert ledger.by_gang.get(IDLE, 0.0) == pytest.approx(0.0, abs=1e-12)
        assert ledger.by_gang[NO_GANG] == pytest.approx(ledger.total_dollars)
        assert ledger.conservation()["ok"]

    def test_prices_pinned_at_launch_survive_book_refresh(self):
        cluster, provider, ledger, clock = ledger_env()
        it = provider.catalog[0]
        off = it.offerings[0]
        cluster.add_node(make_node("n1", it.name, off.zone, off.capacity_type))
        pinned = float(provider.pricing.price(it.name, off.zone, off.capacity_type))
        # a later repricing must not rewrite the meter opened above
        ledger.pricing = None
        clock.step(7200.0)
        ledger.settle()
        assert ledger.total_dollars == pytest.approx(pinned * 2.0)


# ---------------------------------------------------------------------------
# Counterfactual streams + metrics + debug surface
# ---------------------------------------------------------------------------


class TestStreamsAndSurface:
    def test_consolidation_stream_accrues_over_one_window_then_expires(self):
        cluster, provider, ledger, clock = ledger_env(window_s=600.0)
        ledger.note_consolidation(types.SimpleNamespace(savings=3.6))
        clock.step(300.0)
        ledger.settle()
        assert ledger.savings_consolidation == pytest.approx(3.6 * 300 / 3600)
        clock.step(10_000.0)  # far past the horizon: accrual stops at window
        ledger.settle()
        assert ledger.savings_consolidation == pytest.approx(3.6 * 600 / 3600)
        assert ledger.consolidation_actions == 1

    def test_reclaim_and_relaunch_losses(self):
        clock = FakeClock(0.0)
        provider = FakeCloudProvider(catalog=generate_catalog(n_types=6))
        ledger = CostLedger(
            Cluster(), provider.pricing,
            settings=Settings(interruption_penalty_cost=2.5),
            clock=clock, window_s=3600.0,
        ).attach()
        ledger.note_reclaim(("it", "z", wk.CAPACITY_TYPE_SPOT))
        assert ledger.loss_restart_tax == pytest.approx(2.5)
        ledger.note_relaunch(0.10, 0.25)   # $0.15/hr regression
        ledger.note_relaunch(0.30, 0.20)   # improvement: no loss stream
        clock.step(3600.0)
        ledger.settle()
        assert ledger.loss_relaunch == pytest.approx(0.15)
        fed = ledger.federation_fields()
        assert fed["loss_dollars"] == pytest.approx(2.5 + 0.15)

    def test_metrics_refresher_publishes_bounded_series(self):
        cluster, provider, ledger, clock = ledger_env()
        it = provider.catalog[0]
        spot = next(
            o for o in it.offerings
            if o.capacity_type == wk.CAPACITY_TYPE_SPOT
        )
        cluster.add_node(make_node("n1", it.name, spot.zone, spot.capacity_type))
        clock.step(3600.0)
        ledger.publish_metrics()
        got = metrics.COST_DOLLARS.value(
            {"provisioner": "default", "capacity_type": wk.CAPACITY_TYPE_SPOT}
        )
        pinned = float(provider.pricing.price(it.name, spot.zone, spot.capacity_type))
        assert got == pytest.approx(pinned)
        assert metrics.COST_SAVINGS.value({"source": "spot"}) >= 0.0

    def test_debug_costs_endpoint_and_index(self):
        cluster, provider, ledger, clock = ledger_env()
        it = provider.catalog[0]
        off = it.offerings[0]
        cluster.add_node(make_node("n1", it.name, off.zone, off.capacity_type))
        pod = make_pod(name="cl-dbg", cpu="1", labels={wk.POD_GROUP: "g1"})
        cluster.add_pod(pod)
        cluster.bind_pod("cl-dbg", "n1")
        clock.step(1800.0)
        srv = OperatorHTTPServer(port=0, costs=ledger.debug_payload).start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            with urllib.request.urlopen(f"{base}/debug/costs") as r:
                payload = json.loads(r.read())
            assert payload["total_dollars"] > 0
            assert payload["conservation"]["ok"] is True
            assert payload["by_gang"]["g1"]["decisions"] == "/debug/decisions?q=g1"
            with urllib.request.urlopen(
                f"{base}/debug/costs?gang=g1&window=900"
            ) as r:
                filtered = json.loads(r.read())
            assert set(filtered["by_gang"]) == {"g1"}
            assert filtered["windowed"]["window_s"] <= 900
            # the /debug index advertises every route, costs included
            with urllib.request.urlopen(f"{base}/debug") as r:
                index = json.loads(r.read())
            paths = [e["path"] for e in index["routes"]]
            assert "/debug/costs" in paths and "/debug/decisions" in paths
            assert all(e["description"] for e in index["routes"])
        finally:
            srv.stop()

    def test_debug_costs_disabled_without_ledger(self):
        srv = OperatorHTTPServer(port=0).start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/costs"
            ) as r:
                assert json.loads(r.read()) == {"enabled": False}
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# Capsule replay: the per-round ledger delta is a pure function of inputs
# ---------------------------------------------------------------------------


def _spot_round():
    cluster = Cluster()
    provider = FakeCloudProvider(catalog=generate_catalog(n_types=20))
    controller = ProvisioningController(
        cluster, provider, solver=GreedySolver(),
        settings=Settings(
            batch_idle_duration=0, batch_max_duration=0,
            spot_enabled=True, interruption_penalty_cost=0.0,
        ),
    )
    cluster.add_provisioner(make_provisioner())
    for p in make_pods(6, prefix="cl", cpu="500m", memory="1Gi"):
        cluster.add_pod(p)
    result = controller.reconcile()
    assert result.nodes
    capsule = json.loads(json.dumps(FLIGHT.latest("provisioning"), default=str))
    return capsule, result, provider


class TestLedgerReplay:
    def test_round_cost_delta_replays_byte_identical(self):
        capsule, result, provider = _spot_round()
        recorded = capsule["outputs"]["cost_delta"]
        # the capsule carries the delta, and it matches a direct computation
        assert recorded == json.loads(json.dumps(
            round_cost_delta(result.nodes, provider.pricing)
        ))
        report = replay_capsule(capsule, solver="greedy")
        assert report["diffs"]["cost_delta_match"] is True, report["diffs"]
        assert report["match"] is True
        assert json.dumps(report["replayed"]["cost_delta"], sort_keys=True) \
            == json.dumps(recorded, sort_keys=True)

    def test_spot_round_ondemand_counterfactual_strictly_higher(self):
        capsule, result, provider = _spot_round()
        delta = capsule["outputs"]["cost_delta"]
        spot_dollars = delta["per_capacity_type"].get(wk.CAPACITY_TYPE_SPOT, 0.0)
        assert spot_dollars > 0.0  # the round genuinely placed spot
        assert delta["ondemand_per_hr"] > delta["actual_per_hr"]
        assert delta["savings_per_hr"] == pytest.approx(
            delta["ondemand_per_hr"] - delta["actual_per_hr"], abs=2e-6
        )

    def test_price_override_counterfactual_diverges_and_is_flagged(self):
        """``--override offerings=*/*/spot=price:99`` prices every spot pool
        out: the replayed round places on-demand, its ledger delta carries no
        spot savings, and the cost comparison is SKIPPED (counterfactual
        divergence is the point, not a replay failure)."""
        capsule, result, provider = _spot_round()
        recorded = capsule["outputs"]["cost_delta"]
        report = replay_capsule(
            capsule,
            overrides=["offerings=*/*/spot=price:99.0"],
            solver="greedy",
        )
        assert report["counterfactual"] is True
        replayed = report["replayed"]["cost_delta"]
        # spot priced out: the counterfactual spends more and saves nothing
        assert replayed["actual_per_hr"] > recorded["actual_per_hr"]
        assert replayed["per_capacity_type"].get(wk.CAPACITY_TYPE_SPOT, 0.0) == 0.0
        assert replayed["savings_per_hr"] == pytest.approx(0.0, abs=1e-6)
        # the comparison is skipped, not failed
        assert report["diffs"]["cost_delta_match"] is True

    def test_pre_ledger_capsule_skips_cost_comparison(self):
        capsule, _, _ = _spot_round()
        del capsule["outputs"]["cost_delta"]
        report = replay_capsule(capsule, solver="greedy")
        assert report["diffs"]["cost_delta_match"] is True
        assert report["match"] is True
