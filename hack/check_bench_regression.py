"""Bench-regression gate for the incremental-reconcile hot path and the
spot-churn robustness contract.

Runs the ISSUE-3 scenarios plus the ISSUE-7 ``spot_churn`` scenario from
bench.py at reduced scale and FAILS (exit 1) when any regresses past its
floor:

* ``delta_reconcile``: steady-state delta encode must stay >= MIN_SPEEDUP x
  faster than a full re-encode (the acceptance bar is 5x at full 50k scale;
  the gate floor is 3x so box noise can't flap the check), with digest- and
  answer-level equivalence intact (zero violations, identical cost).
* ``consolidation_sweep``: the parallel sweep's chosen action must be
  IDENTICAL to the serial sweep's — any divergence is a correctness bug,
  whatever the timing says.
* ``spot_churn``: sustained scripted reclamation (>= 3 reclaim waves across
  >= 2 spot pools) must end every settle window with ZERO pending pods,
  every victim replaced within the 2-reconcile budget, and mean fleet cost
  <= COST_BAND x the on-demand-only lower bound.
* ``cost_accounting`` (ISSUE 19): the cost ledger's metered total must
  equal the independent offline integration of the node timeline exactly
  (piecewise-constant rates make the trapezoid rule exact), every
  attribution partition must conserve, the ledger-derived
  spend-vs-on-demand fraction must agree with the timeline's and stay
  <= 1.0x on a spot-placing run, and the watch-path overhead (deterministic
  per-event arm) must stay < 5% of the reconcile timeline.
* ``cell_decompose`` (ISSUE 8): every cell's delta encode must stay
  digest-identical to a from-scratch full encode of that cell's canonical
  inputs, the union of per-cell solves must price identically to the flat
  solve under a deterministic solver, and the sharded steady-state round
  must stay >= MIN_CELL_SPEEDUP x faster than the flat round at the same
  scale (churn is cell-local; the flat path re-solves O(cluster) anyway).
* ``cold_solve`` + ``kernel_race`` (ISSUE 9, tightened by ISSUE 14 to the
  literal ROADMAP acceptance): a fresh-batch cold solve in a warm process
  (AOT bucket executables resident) must answer under COLD_SOLVE_MS x
  machine_factor end to end — under ``--full`` that is the 50k fresh batch
  against the literal 100ms acceptance number — and the kernel backend must
  win a race scenario on BOTH axes (cost AND wall-clock) with zero
  constraint violations; under ``--full`` specifically
  ``kernel_race_topology`` at 50k must report ``winner_both: kernel``.
* ``device_staging`` (ISSUE 14): the delta-staging arm — the stager's
  re-uploaded rows must equal the independent host-side diff of
  consecutive rounds' padded tensors (restage count == churned-column
  count), a clean repeat round must move ZERO bytes, and the byte-weighted
  residency hit rate on the 1%-churn scenario must exceed
  STAGING_HIT_RATE_FLOOR.
* ``gang_topology`` (ISSUE 13): on an ICI-coordinate catalog, gangs must
  land on adjacent slices — hop-distance p50 strictly below the
  topology-blind arm's on identical workloads — at cost within
  GANGTOPO_COST_BAND x the unconstrained (blind) optimum, with the
  zero-partial invariant intact; at least one consolidation action must
  move a gang WHOLE, and the scripted preempt-or-launch round must choose
  eviction AND replay byte-identically from its capsule.
* ``device_faults`` (ISSUE 15): a scripted device-fault storm (garbage/NaN
  kernel plans, dispatch hangs, device OOM, staging corruption) must leave
  ZERO invalid bindings, every storm round must complete via host fallback,
  the kernel breaker must trip AND re-close after the faults clear
  (quarantine-evict → half-open re-compile probe), and the validation
  firewall's clean-path overhead must stay < 5% of round p50.
* ``lifecycle_overhead`` (ISSUE 16): the pod-lifecycle stage tracker's
  stamping cost must stay < 5% of round p50. The verdict uses the
  deterministic arm (measured per-pod mark-sequence cost scaled to the
  scenario's pod count) because the ~2% true effect is below round-to-round
  ABBA noise; the raw ABBA pct is reported alongside. The tracked rounds
  must actually produce waterfalls, and the per-stage durations must sum
  to the end-to-end pod-ready latency (ratio ~1.0 — the attribution
  accounts for the FULL latency by construction).
* ``federation_storm`` (ISSUE 17): the 3-cluster federated fleet under the
  canonical fault timeline (regional spot storm, arbiter partition + heal,
  one FULL region blackout + heal) must end every round with ZERO
  unschedulable pods across the surviving clusters, re-enter the lost
  region's gangs elsewhere WHOLE, keep mean fleet cost within
  FED_COST_BAND x the single-global-cluster oracle, and replay every
  captured federation capsule byte-identically — including at least one
  degraded (arbiter-partitioned) round and one post-heal round — with
  zero duplicate-launch audit violations across the epoch fence.
* ``mesh_superproblem`` (ISSUE 18): on a host with >= 2 devices (CI forces
  them via ``--xla_force_host_platform_device_count``), the sharded round
  solved as ONE 2D-meshed superproblem must be kernel-bit-identical to the
  plain single-device path (hence digest-equal placements) with zero
  constraint violations, and the superproblem dispatch must actually
  engage. Wall-clock (meshed round >= the fleet baseline) is gated only on
  real accelerator platforms — forced host devices share the same CPUs.
  Below 2 devices the arm SKIPs VISIBLY (a stderr NOTE, never a vacuous
  pass).
* ``profiler_overhead`` + ``perf_sentinel`` (ISSUE 20): the continuous
  sampling profiler must cost < 5% of round p50 at its default ~19 Hz
  (with the profiler-off rounds verifiably thread-free — zero overhead
  when disabled), and the perf-regression sentinel must catch a scripted
  device-path slowdown (injected dispatch-hang latency, rounds still
  completing) within K rounds of it starting — naming the ``solve`` phase
  and a concrete AOT bucket in the trip — auto-dump an anomaly capsule
  whose collapsed profile contains the dispatch-wait frames, and that
  capsule must replay byte-identically. Vacuousness-guarded both ways:
  zero false trips on the clean rounds BEFORE the fault, and the scripted
  faults must actually have fired with the baseline armed.
* ``soak`` (ISSUE 11): the scaled chaos soak (sustained churn over the
  real-HTTP stack incl. one operator SIGKILL+restart and one apiserver
  restart) must finish with ZERO invariant violations — which covers the
  memory-slope ceiling, pod-ready p99, zero stuck pods, zero duplicate
  launches, zero orphans — every dumped anomaly capsule must replay
  byte-identically offline, and the scenario itself must have churned
  enough (events/s floor, both restart kinds) to mean anything.

Usage:  python hack/check_bench_regression.py [--full]
        (--full runs the acceptance-scale 50k/160 configuration)

Wired into the test suite as a ``slow``-marked pytest
(tests/test_bench_regression.py) so tier-1 stays fast.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

MIN_DELTA_SPEEDUP = 3.0
#: spot_churn: mean fleet cost must stay within this factor of the
#: on-demand-only lower bound (the ISSUE-7 acceptance band)
COST_BAND = 1.5
#: cell_decompose: sharded steady-state round vs the flat round at the same
#: scale (cell-local churn means the sharded path re-solves a couple of
#: cells while flat re-solves the cluster; 2x is a deliberately loose floor
#: so box noise can't flap the gate)
MIN_CELL_SPEEDUP = 2.0
#: cell_fleet: batched-dispatch round p50 vs the per-cell-dispatch baseline
#: on the same sharded workload (ISSUE 12; measured ~1.75x on this 1-CPU
#: box where the fleet win is least expressible — a real accelerator
#: amortizes far more per batched call; the floor leaves noise margin)
MIN_FLEET_SPEEDUP = 1.25
#: cell_fleet: realized round cost, fleet vs per-cell baseline on identical
#: problems — the round-budget share trims host POLISH depth and this band
#: pins that solution quality holds (measured ~1.03-1.05x)
FLEET_COST_BAND = 1.08
#: cell_fleet: the fleet_max_batch the gated bench run uses (the
#: bench_cell_decompose default) — the dispatch-count arm derives its
#: chunk width from this, so gate and measurement can never drift
FLEET_GATE_MAX_BATCH = 16
#: fresh-batch cold solve (warm process, changed batch) end-to-end budget —
#: the ROADMAP item-1 acceptance number
COLD_SOLVE_MS = 100.0
#: device staging: byte-weighted fraction of staged tensor traffic served
#: from device residency on the 1%-churn delta scenario (ISSUE 14
#: acceptance: > 0.9)
STAGING_HIT_RATE_FLOOR = 0.9
#: soak: absolute floor on achieved churn. The acceptance target is 1k
#: events/s on driver-class hardware; the scenario box-calibrates its rate
#: (a sustainable fraction of measured apiserver ingest, capped at 1k) and
#: the gate requires achieving at least half of THAT plus this absolute
#: floor — below either, the soak churned too little to mean anything
#: (vacuousness guard, not the bar)
SOAK_EVENTS_PER_S_FLOOR = 100.0
#: soak: memory-slope ceiling (bytes/second), post-warmup, per incarnation.
#: 512 KiB/s catches the target failure class (unbounded queues/rings run
#: at MB/s under churn) while clearing the decelerating warmup ramp a
#: scaled window cannot fully exclude; the hours-long CLI run gates at
#: 64 KiB/s.
SOAK_MEM_SLOPE_BPS = 524_288.0
#: gang_topology: adjacency-gated gang plan cost vs. the topology-blind
#: arm's unconstrained optimum (the ISSUE-13 acceptance band; coordinates
#: within a domain are price-equal, so measured ~1.0x)
GANGTOPO_COST_BAND = 1.05
#: federation_storm: mean federated fleet cost vs the single-global-cluster
#: oracle (the ISSUE-17 acceptance band; measured ~1.01x at the gated
#: scale — regional fragmentation plus storm/failover churn is what the
#: band absorbs)
FED_COST_BAND = 1.5
#: mesh_superproblem: meshed round p50 vs the fleet-path baseline — gated
#: only on real accelerator platforms (forced host devices share the same
#: CPUs, so sharding buys no silicon and the ratio is noise there)
MESH_SPEEDUP_FLOOR = 1.0


def run_checks(full: bool = False) -> list:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import bench

    failures = []
    if full:
        delta = bench.bench_delta_reconcile()
        sweep = bench.bench_sweep_parallel()
        churn = bench.bench_spot_churn()
        # the 50k tier-adjacent run, flat reference included (the 500k
        # synthetic lives in the main bench, where no flat solve rides along)
        cells = bench.bench_cell_decompose(
            n_pods=50_000, n_cells=10, rounds=5, flat_compare=True
        )
        cold = bench.bench_cold_solve(n_pods=50_000, n_types=400)
        # acceptance-scale topology race: at 50k the host packer's
        # slot arithmetic dwarfs the kernel's group-bound scan, the
        # realistic scenario where the kernel takes BOTH axes
        race_topo_50k = bench.bench_kernel_race_topology(n_pods=50_000)
    else:
        delta = bench.bench_delta_reconcile(n_pods=20_000, rounds=5, n_types=100)
        sweep = bench.bench_sweep_parallel(n_candidates=24)
        churn = bench.bench_spot_churn(n_pods=120, waves=3)
        cells = bench.bench_cell_decompose(
            n_pods=20_000, n_cells=8, rounds=5, n_types=30, flat_compare=True
        )
        cold = bench.bench_cold_solve(n_pods=20_000, n_types=400)
        race_topo_50k = None
    # fleet-dispatch arm (ISSUE 12), flat comparator OFF: the resident flat
    # cluster's memory footprint measurably drags the batched arm on small
    # boxes, and no production sharded operator keeps one — the fleet is
    # gated on the isolated sharded workload both runs share
    cells_fleet = bench.bench_cell_decompose(
        n_pods=20_000, n_cells=8, rounds=8, n_types=30, flat_compare=False
    )
    # cost-ledger accounting arm (ISSUE 19): scenario defaults either way —
    # the verdicts are equalities (metered == integrated, partitions
    # conserve), not wall-clock, so one scale is enough
    costacc = bench.bench_cost_accounting()
    staging = bench.bench_device_staging()
    devfault = bench.bench_device_faults(
        n_pods=20_000 if full else 2_000, n_types=30
    )
    gangtopo = bench.bench_gang_topology()
    # federation survivability (ISSUE 17): one scale either way — the fault
    # timeline needs its full 12 rounds, and the workload must be large
    # enough that regional fragmentation amortizes below the cost band
    fed = bench.bench_federation_storm()
    lifecycle = bench.bench_lifecycle_overhead(
        repeats=6, n_pods=2_000 if full else 300
    )
    # profiler + perf sentinel arms (ISSUE 20): the overhead guard at the
    # default sample rate, and the scripted-slowdown detection scenario —
    # 600 pods is the race_min_pods floor, not a scale choice
    profiler = bench.bench_profiler_overhead(
        repeats=6, n_pods=2_000 if full else 300
    )
    sentinel = bench.bench_perf_sentinel(
        n_pods=2_000 if full else 600,
        warm_rounds=4, slow_rounds=12, n_types=20 if full else 8,
    )
    # meshed superproblem arm (ISSUE 18): needs >= 2 devices — the scenario
    # itself reports a typed skip below that, which the gate surfaces as a
    # stderr NOTE instead of a vacuous pass
    meshed = bench.bench_mesh_superproblem(
        n_pods=50_000 if full else 20_000, n_cells=8,
        rounds=4, n_types=30,
    )
    race = bench.bench_kernel_race()
    race_topo = bench.bench_kernel_race_topology()
    # the chaos soak arm: acceptance-length (>=60 s churn) either way — the
    # scenario is already the scaled version of the hours-long CLI run; the
    # budgets are the monitor's defaults (its violations list is the gate).
    # 75 s (not the bare 60) keeps the post-kill incarnation's memory window
    # comfortably past the leak detector's warmup + min-span rules.
    soak = bench.bench_soak(
        duration_s=75.0 if not full else 90.0,
        mem_slope_budget_bps=SOAK_MEM_SLOPE_BPS,
    )
    print(json.dumps({
        "delta_reconcile": delta, "consolidation_sweep": sweep,
        "spot_churn": churn, "cost_accounting": costacc,
        "cell_decompose": cells,
        "cell_fleet": cells_fleet, "gang_topology": gangtopo,
        "device_staging": staging, "device_faults": devfault,
        "lifecycle_overhead": lifecycle,
        "profiler_overhead": profiler, "perf_sentinel": sentinel,
        "cold_solve": cold, "kernel_race": race,
        "kernel_race_topology": race_topo,
        "kernel_race_topology_50k": race_topo_50k,
        "federation_storm": fed,
        "mesh_superproblem": meshed,
        "soak": soak,
    }, default=str))

    if delta.get("encode_speedup", 0.0) < MIN_DELTA_SPEEDUP:
        failures.append(
            f"delta_reconcile encode speedup {delta.get('encode_speedup')}x "
            f"< floor {MIN_DELTA_SPEEDUP}x"
        )
    if not delta.get("digests_equal", False):
        failures.append("delta-encoded problem diverged from full encode (digest)")
    if not delta.get("cost_equal", False):
        failures.append(
            f"delta/full answers diverged: {delta.get('cost_per_hour_delta')} "
            f"vs {delta.get('cost_per_hour_full')}"
        )
    if delta.get("violations", 1) != 0:
        failures.append(f"delta_reconcile produced {delta.get('violations')} violations")
    if delta.get("delta_rounds", 0) < delta.get("rounds", 1):
        failures.append(
            f"only {delta.get('delta_rounds')}/{delta.get('rounds')} rounds took "
            "the delta path — the session is falling back to full encodes"
        )
    if not sweep.get("actions_equal", False):
        failures.append(
            "parallel consolidation sweep diverged from the serial action: "
            f"{sweep.get('chosen_action')!r}"
        )
    # -- spot_churn gate (ISSUE 7) ------------------------------------------
    if churn.get("unschedulable_p100", 1) != 0:
        failures.append(
            f"spot_churn left {churn.get('unschedulable_p100')} pods pending "
            "at steady state (must be zero under sustained reclamation)"
        )
    if churn.get("max_rounds_to_replace", 99) > churn.get("replace_budget", 2):
        failures.append(
            f"spot_churn victims took {churn.get('max_rounds_to_replace')} "
            f"reconcile rounds to replace (budget "
            f"{churn.get('replace_budget', 2)})"
        )
    if churn.get("reclaims_survived", 0) < 3 or churn.get("pools_reclaimed", 0) < 2:
        failures.append(
            "spot_churn exercised too little churn "
            f"(reclaims={churn.get('reclaims_survived')}, "
            f"pools={churn.get('pools_reclaimed')}) — the scenario itself "
            "regressed, the gate is vacuous"
        )
    frac = churn.get("cost_vs_ondemand_frac")
    if frac is None or frac > COST_BAND:
        failures.append(
            f"spot_churn mean cost {frac}x the on-demand-only lower bound "
            f"(band {COST_BAND}x)"
        )
    # -- cost_accounting gate (ISSUE 19) ------------------------------------
    if (
        costacc.get("reclaims", 0) < 3
        or costacc.get("spot_savings_dollars", 0.0) <= 0.0
        or costacc.get("watch_events", 0) < 100
    ):
        failures.append(
            "cost_accounting exercised too little churn "
            f"(reclaims={costacc.get('reclaims')}, "
            f"spot_savings={costacc.get('spot_savings_dollars')}, "
            f"events={costacc.get('watch_events')}) — the scenario itself "
            "regressed, the gate is vacuous"
        )
    if not costacc.get("conservation_ok", False):
        failures.append(
            "cost_accounting: ledger partitions do not conserve "
            f"(max_abs_error={costacc.get('conservation_max_abs_error')})"
        )
    if not costacc.get("integration_equal", False):
        failures.append(
            "cost_accounting: metered total diverged from the independent "
            f"offline integration ({costacc.get('ledger_dollars')} vs "
            f"{costacc.get('offline_dollars')}, "
            f"err={costacc.get('integration_abs_err')})"
        )
    if not costacc.get("frac_consistent", False):
        failures.append(
            "cost_accounting: ledger-derived spend-vs-on-demand fraction "
            f"({costacc.get('ledger_vs_ondemand_frac')}) disagrees with the "
            f"offline timeline's ({costacc.get('offline_vs_ondemand_frac')})"
        )
    led_frac = costacc.get("ledger_vs_ondemand_frac")
    if led_frac is None or led_frac > 1.0 + 1e-6:
        failures.append(
            f"cost_accounting: realized spend {led_frac}x the on-demand "
            "counterfactual — a spot-placing timeline must never exceed 1.0x"
        )
    if not costacc.get("within_overhead_budget", False):
        failures.append(
            "cost_accounting: ledger watch-path overhead "
            f"{costacc.get('ledger_overhead_pct')}% of the reconcile "
            f"timeline (per event {costacc.get('per_event_us')}us) "
            "exceeds the 5% budget"
        )
    # -- cell_decompose gate (ISSUE 8) --------------------------------------
    if not cells.get("digests_equal", False):
        failures.append(
            "cell_decompose: a cell's delta encode diverged from the "
            "from-scratch full encode of its canonical inputs (digest)"
        )
    if not cells.get("cost_equal", False):
        failures.append(
            "cell_decompose: decomposed/flat answers diverged: "
            f"{cells.get('cost_cells')} vs {cells.get('cost_flat')}"
        )
    if cells.get("speedup_vs_flat", 0.0) < MIN_CELL_SPEEDUP:
        failures.append(
            f"cell_decompose round speedup {cells.get('speedup_vs_flat')}x "
            f"< floor {MIN_CELL_SPEEDUP}x"
        )
    # -- fleet-dispatch gate (ISSUE 12) -------------------------------------
    # one vmapped device call per distinct bucket instead of one per cell:
    # the fleet must actually engage (>=2 cells batched per round), the
    # per-round device-dispatch count must stay O(distinct buckets), the
    # batched kernel must be bit-identical to the per-cell kernel, the
    # batched round must beat the per-cell-dispatch baseline by the floor,
    # and the round-budget share must not buy that wall clock with solution
    # quality beyond the band.
    if (cells_fleet.get("fleet_cells_batched_p50") or 0) < 2:
        failures.append(
            "cell_fleet: fleet dispatch not exercised (cells batched p50 "
            f"{cells_fleet.get('fleet_cells_batched_p50')} < 2)"
        )
    # O(distinct buckets) with the chunking caveat: a bucket whose group
    # exceeds the pow2 width cap legitimately splits into ceil(cells/cap)
    # dispatches — the cap derives from the same fleet_max_batch the
    # gated bench run dispatches with
    _wcap = 1 << (FLEET_GATE_MAX_BATCH.bit_length() - 1)
    _chunks = max(
        1,
        -(-int(cells_fleet.get("fleet_cells_batched_p50") or 0) // _wcap),
    )
    if (cells_fleet.get("fleet_dispatches_p50") or 0) > _chunks * (
        cells_fleet.get("fleet_distinct_buckets_p50") or 0
    ):
        failures.append(
            "cell_fleet: device dispatches per round "
            f"{cells_fleet.get('fleet_dispatches_p50')} exceed distinct "
            f"buckets {cells_fleet.get('fleet_distinct_buckets_p50')} "
            f"(x{_chunks} width-cap chunks)"
        )
    if cells_fleet.get("fleet_equal") is not True:
        failures.append(
            "cell_fleet: batched fleet kernel diverged from serial "
            "per-cell dispatch (must be bit-identical)"
        )
    if (cells_fleet.get("fleet_speedup") or 0.0) < MIN_FLEET_SPEEDUP:
        failures.append(
            f"cell_fleet: batched round speedup "
            f"{cells_fleet.get('fleet_speedup')}x vs the per-cell-dispatch "
            f"baseline < floor {MIN_FLEET_SPEEDUP}x"
        )
    if (cells_fleet.get("fleet_cost_vs_serial_frac") or 1.0) > FLEET_COST_BAND:
        failures.append(
            f"cell_fleet: fleet round cost "
            f"{cells_fleet.get('fleet_cost_vs_serial_frac')}x the per-cell "
            f"baseline's (band {FLEET_COST_BAND}x) — the round-budget share "
            "is buying wall clock with solution quality"
        )
    if not cells_fleet.get("digests_equal", False):
        failures.append(
            "cell_fleet: a cell's delta encode diverged from its "
            "from-scratch oracle under the fleet path"
        )
    # -- gang_topology gate (ISSUE 13) ---------------------------------------
    hop = gangtopo.get("hop_p50")
    hop_blind = gangtopo.get("hop_p50_blind")
    if hop is None or hop_blind is None or not hop < hop_blind:
        failures.append(
            f"gang_topology: adjacency hop p50 {hop} not strictly below the "
            f"topology-blind baseline {hop_blind}"
        )
    gfrac = gangtopo.get("cost_vs_blind_frac")
    if gfrac is None or gfrac > GANGTOPO_COST_BAND:
        failures.append(
            f"gang_topology: adjacency plan cost {gfrac}x the unconstrained "
            f"optimum (band {GANGTOPO_COST_BAND}x)"
        )
    if (gangtopo.get("adjacency_win_rate") or 0.0) <= 0.0:
        failures.append(
            "gang_topology: no gang landed whole in one ICI domain "
            f"(win rate {gangtopo.get('adjacency_win_rate')})"
        )
    if not gangtopo.get("zero_partial", False):
        failures.append(
            "gang_topology: a gang was observed PARTIALLY placed (the "
            "all-or-nothing invariant broke under topology packing)"
        )
    if (gangtopo.get("gang_moves_whole") or 0) < 1:
        failures.append(
            "gang_topology: consolidation moved no gang whole — the "
            "gang-aware sweep regressed (or the scenario is vacuous)"
        )
    if (gangtopo.get("preempt_or_launch_evictions") or 0) < 1:
        failures.append(
            "gang_topology: preempt-or-launch chose eviction in no scripted "
            "round (the cost decision regressed)"
        )
    if gangtopo.get("preempt_replay_match") is not True:
        failures.append(
            "gang_topology: the preempt-or-launch round did not replay "
            "byte-identically from its capsule"
        )
    # -- cold-solve + kernel-race gate (ISSUE 9) -----------------------------
    # the 100ms acceptance budget is a driver-box number; the gate scales it
    # by the box's measured fresh-encode rate against the driver anchor
    # (bench_cold_solve.machine_factor — 1.0 on driver-class hardware, so
    # there the gate IS the literal acceptance criterion)
    cold_ms = cold.get("cold_solve_ms")
    budget = COLD_SOLVE_MS * cold.get("machine_factor", 1.0)
    if cold_ms is None or cold_ms >= budget:
        failures.append(
            f"cold_solve fresh-batch {cold_ms}ms at {cold.get('pods')} pods "
            f">= budget {round(budget, 1)}ms "
            f"(100ms x machine_factor {cold.get('machine_factor')})"
        )
    if cold.get("unschedulable", 1) != 0:
        failures.append(
            f"cold_solve stranded {cold.get('unschedulable')} pods"
        )
    kernel_wins_both = any(
        r.get("winner_both") == "kernel"
        for r in (race, race_topo, race_topo_50k)
        if r is not None
    )
    if not kernel_wins_both:
        failures.append(
            "kernel backend won no race scenario on BOTH axes "
            f"(kernel_race: cost={race.get('winner_cost')} "
            f"wall={race.get('winner_wall')}; kernel_race_topology: "
            f"cost={race_topo.get('winner_cost')} "
            f"wall={race_topo.get('winner_wall')})"
        )
    if full and race_topo_50k is not None and (
        race_topo_50k.get("winner_both") != "kernel"
    ):
        # the literal ROADMAP acceptance (tightened by ISSUE 14): at 50k the
        # realistic topology race must flip to the kernel on BOTH axes —
        # a win in some other scenario no longer substitutes under --full
        failures.append(
            "kernel_race_topology@50k winner_both is "
            f"{race_topo_50k.get('winner_both')!r}, not 'kernel' "
            f"(cost={race_topo_50k.get('winner_cost')} "
            f"wall={race_topo_50k.get('winner_wall')}) — the acceptance-"
            "scale race verdict regressed"
        )
    # -- device-staging gate (ISSUE 14) --------------------------------------
    if staging.get("restage_matches_churn") is not True:
        failures.append(
            "device_staging: restaged rows diverged from the independent "
            f"churn diff ({staging.get('restaged_rows_total')} restaged vs "
            f"{staging.get('expected_rows_total')} churned) — the stager is "
            "moving the wrong rows"
        )
    if staging.get("clean_repeat_restages", 1) != 0 or staging.get(
        "clean_repeat_transfer_bytes", 1
    ) != 0:
        failures.append(
            "device_staging: a clean repeat round moved "
            f"{staging.get('clean_repeat_transfer_bytes')} bytes "
            f"({staging.get('clean_repeat_restages')} restages) — an "
            "unchanged problem must stage zero"
        )
    if (staging.get("staging_hit_rate") or 0.0) <= STAGING_HIT_RATE_FLOOR:
        failures.append(
            f"device_staging: residency hit rate "
            f"{staging.get('staging_hit_rate')} <= floor "
            f"{STAGING_HIT_RATE_FLOOR} on the 1%-churn delta scenario"
        )
    for label, r in (
        ("kernel_race_topology", race_topo),
        ("kernel_race_topology_50k", race_topo_50k),
    ):
        if r is not None and r.get("violations", 1) != 0:
            failures.append(
                f"{label} produced {r.get('violations')} constraint violations"
            )
    # -- device-faults gate (ISSUE 15) ---------------------------------------
    if devfault.get("invalid_bindings", 1) != 0:
        failures.append(
            f"device_faults: {devfault.get('invalid_bindings')} INVALID "
            "bindings reached cluster state under the fault storm (the "
            "validation firewall's zero-invalid-bindings contract broke)"
        )
    if devfault.get("rounds_completed", 0) < devfault.get("storm_rounds", 1):
        failures.append(
            f"device_faults: only {devfault.get('rounds_completed')}/"
            f"{devfault.get('storm_rounds')} storm rounds completed via "
            "host fallback (a device fault failed a round)"
        )
    if devfault.get("breaker_reclosed") is not True:
        failures.append(
            "device_faults: the kernel breaker did not re-close after the "
            "faults cleared (half-open re-compile probe regressed)"
        )
    if devfault.get("breaker_tripped") is not True:
        failures.append(
            "device_faults: the storm never tripped the kernel breaker — "
            "the scenario regressed, the gate is vacuous"
        )
    if devfault.get("faults_fired", 0) < 3:
        failures.append(
            f"device_faults: only {devfault.get('faults_fired')} scripted "
            "faults actually fired — the injection seams regressed"
        )
    vo = devfault.get("validator_overhead_pct")
    if vo is None or vo >= 5.0:
        failures.append(
            f"device_faults: validation-firewall clean-path overhead {vo}% "
            ">= the 5% budget of round p50"
        )
    # -- lifecycle-attribution gate (ISSUE 16) --------------------------------
    lo = lifecycle.get("stamping_overhead_est_pct")
    if lo is None or lo >= 5.0:
        failures.append(
            f"lifecycle_overhead: tracker stamping cost {lo}% of round p50 "
            f"(deterministic per-pod arm, "
            f"{lifecycle.get('stamping_per_pod_us')}us/pod) >= the 5% budget"
        )
    if (lifecycle.get("waterfalls") or 0) < 1:
        failures.append(
            "lifecycle_overhead: tracked rounds produced no completed "
            "waterfalls — the scenario regressed, the gate is vacuous"
        )
    ratio = lifecycle.get("stage_sum_over_e2e")
    if ratio is None or abs(ratio - 1.0) > 0.05:
        failures.append(
            f"lifecycle_overhead: per-stage durations sum to {ratio}x the "
            "end-to-end pod-ready latency (must be ~1.0: the waterfall "
            "attribution is leaking unaccounted time)"
        )
    if not lifecycle.get("dominant_stage"):
        failures.append(
            "lifecycle_overhead: no dominant stage named — stage "
            "attribution produced no segments"
        )
    # -- profiler gate (ISSUE 20) ---------------------------------------------
    po = profiler.get("prof_overhead_pct")
    if po is None or po >= 5.0:
        failures.append(
            f"profiler_overhead: sampler cost {po}% of round p50 at the "
            f"default {profiler.get('sample_hz')} Hz >= the 5% budget"
        )
    if profiler.get("profiler_off_thread_alive") is not False:
        failures.append(
            "profiler_overhead: a sampler thread was alive during the "
            "profiler-OFF rounds — the zero-overhead-when-disabled "
            "contract broke (or the off arm never measured it)"
        )
    if sentinel.get("detected_within_k") is not True:
        failures.append(
            "perf_sentinel: the scripted dispatch slowdown was detected in "
            f"{sentinel.get('detected_in_rounds')} rounds, not within "
            f"K={sentinel.get('mad_k')} of it starting"
        )
    if sentinel.get("trip_phase") != "solve":
        failures.append(
            f"perf_sentinel: trip named phase {sentinel.get('trip_phase')!r} "
            "— the dispatch-hang slowdown must attribute to 'solve'"
        )
    if not sentinel.get("trip_bucket"):
        failures.append(
            "perf_sentinel: trip named no AOT bucket — the per-bucket "
            "attribution half of the DecisionRecord regressed"
        )
    if sentinel.get("capsule_dumped") is not True or sentinel.get(
        "capsule_trigger_ok"
    ) is not True:
        failures.append(
            "perf_sentinel: no anomaly capsule auto-dumped with the "
            "perf-regression trigger "
            f"(dumped={sentinel.get('capsule_dumped')}, "
            f"trigger={sentinel.get('capsule_trigger_ok')})"
        )
    if sentinel.get("profile_has_dispatch_path") is not True:
        failures.append(
            "perf_sentinel: the capsule's collapsed profile contains no "
            "dispatch-wait frames (_poll_dispatch/_fetch_bounded) — the "
            "trip's forensic profile window missed the slow path"
        )
    if sentinel.get("capsule_replay_match") is not True:
        failures.append(
            "perf_sentinel: the perf-regression capsule did not replay "
            "byte-identically (the forensic profile fields must ride "
            "OUTSIDE the replay comparison)"
        )
    if sentinel.get("false_trips", 1) != 0:
        failures.append(
            f"perf_sentinel: {sentinel.get('false_trips')} trip(s) fired on "
            "the clean rounds BEFORE the fault — the sentinel false-trips "
            "on a healthy pipeline"
        )
    if (
        sentinel.get("baseline_armed") is not True
        or sentinel.get("faults_fired", 0) < 1
    ):
        failures.append(
            "perf_sentinel exercised too little "
            f"(baseline_armed={sentinel.get('baseline_armed')}, "
            f"faults_fired={sentinel.get('faults_fired')}) — the scenario "
            "regressed, the gate is vacuous"
        )
    # -- federation-storm gate (ISSUE 17) -------------------------------------
    if fed.get("fed_unschedulable_p100", 1) != 0:
        failures.append(
            f"federation_storm left {fed.get('fed_unschedulable_p100')} pods "
            "unschedulable at a round end across the surviving clusters "
            "(must be zero under regional loss)"
        )
    if fed.get("fed_gangs_reentered_whole") is not True:
        failures.append(
            "federation_storm: the lost region's gangs did not all re-enter "
            f"a surviving cluster WHOLE ({fed.get('gangs_failed_over')} "
            "failed over) — the all-or-nothing regional failover broke"
        )
    ffrac = fed.get("fed_cost_vs_oracle_frac")
    if ffrac is None or ffrac > FED_COST_BAND:
        failures.append(
            f"federation_storm mean cost {ffrac}x the single-global-cluster "
            f"oracle (band {FED_COST_BAND}x)"
        )
    if fed.get("fed_replay_all_matched") is not True:
        failures.append(
            "federation_storm: not every captured federation capsule "
            "replayed byte-identically (verdict digest or a per-cluster "
            "sub-capsule diverged)"
        )
    if fed.get("audit_violations", 1) != 0:
        failures.append(
            f"federation_storm: {fed.get('audit_violations')} duplicate-"
            "launch audit violations — a lease token was live in two "
            "running clusters at once (the epoch fence broke)"
        )
    # vacuousness guards: the scenario must have actually blacked out a
    # region, failed gangs over, granted leases, and captured BOTH failure
    # shapes (>=1 degraded round, >=1 post-heal round) in the replayed set
    if (
        fed.get("blackouts", 0) < 1
        or fed.get("gangs_failed_over", 0) < 1
        or fed.get("leases_granted", 0) < 1
    ):
        failures.append(
            "federation_storm exercised too little chaos "
            f"(blackouts={fed.get('blackouts')}, "
            f"gangs_failed_over={fed.get('gangs_failed_over')}, "
            f"leases_granted={fed.get('leases_granted')}) — the scenario "
            "regressed, the gate is vacuous"
        )
    if fed.get("degraded_rounds", 0) < 1 or fed.get(
        "degraded_round_replays", 0
    ) < 1:
        failures.append(
            "federation_storm captured no degraded (arbiter-partitioned) "
            "round — the partition-tolerant degradation arm is vacuous"
        )
    if fed.get("post_heal_replays", 0) < 1:
        failures.append(
            "federation_storm captured no post-heal round — the rejoin "
            "epoch-fence arm is vacuous"
        )
    # -- meshed superproblem gate (ISSUE 18) ---------------------------------
    if meshed.get("skipped"):
        # below 2 devices (or with the mesh disabled by the platform) the arm
        # cannot run at all — a VISIBLE skip, never a vacuous pass. CI that
        # wants the arm forces host devices via
        # XLA_FLAGS=--xla_force_host_platform_device_count=4.
        print(
            f"NOTE: mesh_superproblem arm skipped ({meshed['skipped']}): "
            f"needs >= 2 devices, have {meshed.get('device_count')}",
            file=sys.stderr,
        )
    else:
        if meshed.get("super_equal") is not True:
            failures.append(
                "mesh_superproblem: 2D-meshed superproblem kernel diverged "
                f"from the single-device path (super_equal="
                f"{meshed.get('super_equal')!r})"
            )
        if meshed.get("violations", 1) != 0:
            failures.append(
                f"mesh_superproblem produced {meshed.get('violations')} "
                "constraint violations"
            )
        # vacuousness guards: the meshed arm must have actually dispatched
        # superproblems onto a 2D mesh — otherwise it silently degraded to
        # the fleet path and every assertion above gated nothing
        if (meshed.get("superproblems_p50") or 0) < 1:
            failures.append(
                "mesh_superproblem dispatched no superproblems "
                f"(superproblems_p50={meshed.get('superproblems_p50')}) — "
                "the round degraded to the fleet path, the gate is vacuous"
            )
        if not meshed.get("mesh_axes"):
            failures.append(
                "mesh_superproblem ran without a 2D mesh (mesh_axes empty) "
                "— the arm is vacuous"
            )
        # wall-clock only on real accelerators: forced host devices share
        # the same CPUs, so the meshed/fleet ratio is pure noise there
        if meshed.get("platform") not in (None, "cpu"):
            speedup = meshed.get("super_speedup") or 0.0
            if speedup < MESH_SPEEDUP_FLOOR:
                failures.append(
                    f"mesh_superproblem meshed round {speedup}x the fleet "
                    f"baseline (floor {MESH_SPEEDUP_FLOOR}x on "
                    f"{meshed.get('platform')})"
                )
    # -- chaos soak gate (ISSUE 11) ------------------------------------------
    if soak.get("skipped_busy_box"):
        # the PR 12 contention note, made explicit (ISSUE 14): a box already
        # running a heavy concurrent process stretches the soak's wall-clock
        # contracts into false invariant failures — the pre-flight probe
        # degrades the arm to a VISIBLE skip instead. Every soak assertion
        # below is bypassed; run the gate on an idle box for the real arm.
        print(
            "NOTE: soak arm skipped (busy box): "
            f"{soak.get('reason')}", file=sys.stderr,
        )
        return failures
    if soak.get("invariant_violations", 1) != 0:
        failures.append(
            f"soak tripped {soak.get('invariant_violations')} invariant(s): "
            f"{soak.get('violations')}"
        )
    if not soak.get("replay_all_matched", False):
        failures.append(
            "soak anomaly capsules did not all replay byte-identically: "
            f"{soak.get('replay')}"
        )
    if soak.get("mem_slope_bytes_per_s", 1e18) > SOAK_MEM_SLOPE_BPS:
        failures.append(
            f"soak memory slope {soak.get('mem_slope_kib_per_s')} KiB/s over "
            f"the {SOAK_MEM_SLOPE_BPS / 1024:.0f} KiB/s ceiling"
        )
    # vacuousness guards: the soak must have actually churned, actually
    # killed+revived the operator, actually bounced the apiserver, and the
    # leak detector must have had at least one qualifying window to judge
    restarts = soak.get("restarts", {})
    rate_floor = max(SOAK_EVENTS_PER_S_FLOOR, 0.5 * soak.get("rate_hz", 0.0))
    if soak.get("events_per_s", 0.0) < rate_floor:
        failures.append(
            f"soak churned only {soak.get('events_per_s')} events/s "
            f"(floor {round(rate_floor, 1)} = max({SOAK_EVENTS_PER_S_FLOOR}, "
            f"half the calibrated {soak.get('rate_hz')}/s target)) — the "
            "scenario regressed, the gate is vacuous"
        )
    if restarts.get("operator_kill", 0) < 1 or restarts.get("apiserver", 0) < 1:
        failures.append(
            f"soak exercised too little chaos (restarts={restarts}) — it "
            "must include >=1 operator SIGKILL and >=1 apiserver restart"
        )
    if soak.get("mem_segments", 0) < 1:
        failures.append(
            "soak leak detector had no qualifying memory window "
            "(mem_segments=0) — lengthen the run, the slope arm is vacuous"
        )
    return failures


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--full", action="store_true",
                        help="acceptance-scale run (50k pods / 160 candidates)")
    args = parser.parse_args()
    failures = run_checks(full=args.full)
    if failures:
        for f in failures:
            print(f"REGRESSION: {f}", file=sys.stderr)
        return 1
    print("bench regression gate: OK", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
