"""Settings/doc/deploy drift gate — ``hack/docs`` verification for the
settings surface.

Checks, in EVERY direction, that the three places a setting must appear agree:

* every ``Settings`` dataclass field has a row in the generated
  ``docs/settings.md`` (run ``python hack/gen_docs.py`` to refresh);
* every documented row names a field that still exists;
* every field has a ``KARPENTER_TPU_<NAME>`` key in the deploy ConfigMap
  manifest(s) (``deploy/manifests/configmap-*-global-settings.yaml`` — run
  ``python deploy/render.py --out-dir deploy/manifests`` to refresh);
* every ConfigMap key maps back to a live field (a deleted setting must take
  its manifest key with it — a stale env key would fail ``Settings.from_env``
  at operator boot, the worst place to discover drift).

Wired as a tier-1 test (``tests/test_settings_docs.py``), same pattern as
``check_metrics_docs.py`` / ``check_debug_endpoints.py``, and runnable
standalone::

    python hack/check_settings_docs.py   # exits 1 and prints the drift
"""

from __future__ import annotations

import glob
import os
import re
import sys
from dataclasses import fields
from typing import Dict, List

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
DOC = os.path.join(ROOT, "docs", "settings.md")
MANIFEST_GLOB = os.path.join(
    ROOT, "deploy", "manifests", "configmap-*-global-settings.yaml"
)

_ROW = re.compile(r"^\|\s*`([a-z0-9_]+)`\s*\|")
_ENV_PREFIX = "KARPENTER_TPU_"


def declared_settings() -> List[str]:
    from karpenter_tpu.api.settings import Settings

    return [f.name for f in fields(Settings) if not f.name.startswith("_")]


def documented_settings(path: str = DOC) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [m.group(1) for line in f if (m := _ROW.match(line.strip()))]


def configmap_keys() -> Dict[str, List[str]]:
    """{manifest path: [env keys]} for every global-settings ConfigMap."""
    import yaml

    out: Dict[str, List[str]] = {}
    for path in sorted(glob.glob(MANIFEST_GLOB)):
        with open(path) as f:
            obj = yaml.safe_load(f)
        out[path] = sorted((obj or {}).get("data", {}).keys())
    return out


def check() -> List[str]:
    """Every drift problem as a human-readable line; empty means clean."""
    declared = declared_settings()
    documented = documented_settings()
    problems: List[str] = []
    for name in declared:
        if name not in documented:
            problems.append(
                f"setting {name} missing from docs/settings.md "
                "(run python hack/gen_docs.py)"
            )
    for name in documented:
        if name not in declared:
            problems.append(
                f"docs/settings.md documents {name} which no longer exists "
                "(run python hack/gen_docs.py)"
            )
    manifests = configmap_keys()
    if not manifests:
        problems.append(f"no global-settings ConfigMap manifest matches {MANIFEST_GLOB}")
    env_of = {f"{_ENV_PREFIX}{n.upper()}": n for n in declared}
    from karpenter_tpu.api.settings import Settings

    defaults = Settings(cluster_name="drift-check")
    for path, keys in manifests.items():
        rel = os.path.relpath(path, ROOT)
        for name in declared:
            # the renderer omits fields whose default is None or a mapping
            # (deploy/render.py settings_configmap) — mirror that rule, or
            # the gate flags manifests the renderer itself just produced
            v = getattr(defaults, name)
            if v is None or isinstance(v, dict):
                continue
            key = f"{_ENV_PREFIX}{name.upper()}"
            if key not in keys:
                problems.append(
                    f"setting {name} missing from {rel} as {key} "
                    "(run python deploy/render.py --out-dir deploy/manifests)"
                )
        for key in keys:
            if key not in env_of:
                problems.append(
                    f"{rel} carries {key} which maps to no Settings field "
                    "(run python deploy/render.py --out-dir deploy/manifests)"
                )
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"DRIFT: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"settings docs current: {len(declared_settings())} settings checked")
    return 0


if __name__ == "__main__":
    sys.exit(main())
