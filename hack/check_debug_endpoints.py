"""Debug-endpoint/doc drift gate — every ``/debug/*`` route must be documented.

The operator's HTTP surface (``karpenter_tpu/utils/httpserver.py``) is the
operator's primary debugging interface; a route that exists but is absent
from ``docs/observability.md`` is a feature nobody will find. This gate
checks, in BOTH directions, that the routes registered on the HTTP handler
and the endpoints documented in the runbook agree:

* every ``/debug/*`` route string in the handler appears in
  ``docs/observability.md``;
* every ``/debug/*`` path mentioned in the doc still exists in the handler
  (a removed route must take its doc with it).

Wired as a tier-1 test (``tests/test_debug_endpoints_docs.py``) like the
metrics gate, and runnable standalone::

    python hack/check_debug_endpoints.py   # exits 1 and prints the drift
"""

from __future__ import annotations

import os
import re
import sys
from typing import List, Set

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
SERVER = os.path.join(ROOT, "karpenter_tpu", "utils", "httpserver.py")
DOC = os.path.join(ROOT, "docs", "observability.md")

#: a /debug route literal in the handler (string compares / startswith
#: prefixes both match; the trailing slash of a prefix route is stripped)
_ROUTE = re.compile(r'"(/debug/[a-z_]+)/?"')

#: a route the handler actually DISPATCHES on (an equality compare or a
#: prefix startswith) — distinct from _ROUTE, which also matches the
#: DEBUG_ROUTES table literals and would make table-vs-handler vacuous
_HANDLER = re.compile(
    r'path\s*==\s*"(/debug/[a-z_]+)"|path\.startswith\("(/debug/[a-z_]+)/"\)'
)


def registered_routes(path: str = SERVER) -> Set[str]:
    with open(path) as f:
        source = f.read()
    return set(_ROUTE.findall(source))


def handler_routes(path: str = SERVER) -> Set[str]:
    """Routes with a real dispatch branch in the handler."""
    with open(path) as f:
        source = f.read()
    return {a or b for a, b in _HANDLER.findall(source)}


def table_routes() -> Set[str]:
    """The DEBUG_ROUTES index table — the source of truth ``GET /debug``
    serves; imported live so the gate and the index can never disagree."""
    from karpenter_tpu.utils.httpserver import DEBUG_ROUTES

    return set(DEBUG_ROUTES)


def documented_routes(path: str = DOC) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        text = f.read()
    return set(_ROUTE.findall(text)) | set(
        re.findall(r"`(/debug/[a-z_]+)", text)
    )


def check() -> List[str]:
    """Every drift problem as a human-readable line; empty means clean."""
    registered = registered_routes()
    documented = documented_routes()
    problems = []
    for route in sorted(registered - documented):
        problems.append(
            f"route {route} is served by utils/httpserver.py but not "
            "documented in docs/observability.md"
        )
    for route in sorted(documented - registered):
        problems.append(
            f"docs/observability.md documents {route} which is not "
            "registered on the HTTP surface"
        )
    # the GET /debug index table must track the handler branches exactly
    table = table_routes()
    handler = handler_routes()
    for route in sorted(handler - table):
        problems.append(
            f"route {route} has a handler branch but no DEBUG_ROUTES index "
            "entry (GET /debug would not list it)"
        )
    for route in sorted(table - handler):
        problems.append(
            f"DEBUG_ROUTES lists {route} but no handler branch serves it "
            "(GET /debug advertises a 404)"
        )
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"DRIFT: {p}", file=sys.stderr)
    if problems:
        return 1
    print(
        f"debug endpoint docs current: {len(registered_routes())} routes checked"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
