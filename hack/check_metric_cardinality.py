"""Metric label-cardinality lint — every label key must be enumerated here.

Prometheus series are born from label VALUES, but runaway cardinality always
arrives through a label KEY that names an unbounded identity space: a pod
name, a node name, a machine id, a trace id. One such key turns a fleet of
100k pods into 100k series per metric and takes the scrape path down. This
gate makes the label-key space a closed, reviewed set:

* every dict literal passed to a metric mutator (``.inc``/``.set``/
  ``.observe``/``.time``) or to ``series_key`` anywhere in the package must
  use keys from ``ALLOWED_LABEL_KEYS``;
* identity-shaped keys (``FORBIDDEN_LABEL_KEYS``) are rejected everywhere —
  with ONE documented exemption: the fleet-state gauges in
  ``controllers/metricsscraper/`` carry ``node_name`` because they publish
  via ``replace_series`` full swaps and registry-refresher pruning, so
  their series set is bounded by the LIVE fleet, never by history;
* a non-constant (computed) key in a metric label literal is rejected
  outright — a computed key is an unreviewable cardinality hole. ``**``
  spreads are skipped: the spread dict's own literal is checked where it is
  built.

Static by design (AST over source, no imports): the lint sees call sites
that only fire on rare paths a test run never visits. Wired as a tier-1
test (``tests/test_metric_cardinality.py``) like the other drift gates, and
runnable standalone::

    python hack/check_metric_cardinality.py   # exits 1 and prints offenders
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(ROOT, "karpenter_tpu")

#: metric mutators whose first positional arg / ``labels=`` kwarg is a label
#: dict; ``series_key`` builds the same label identity for set_series /
#: replace_series views
_METRIC_METHODS = {"inc", "set", "observe", "time"}

#: The closed label-key vocabulary. Adding a key here is a REVIEWED act:
#: every key must name a bounded enum-like dimension (capacity types, stage
#: names, reasons, outcome verdicts), never an object identity.
ALLOWED_LABEL_KEYS = {
    "action",         # backpressure/queue actions (shed, coalesce, ...)
    "axes",           # mesh axis layouts (2D shapes, tiny enum)
    "batcher",        # 'pod' | 'rpc'
    "bucket",         # AOT size buckets (log-scaled, bounded)
    "capacity_type",  # spot | on-demand
    "cell",           # control-plane cells (bounded by cell_max_count)
    "cluster",        # federation member clusters (config-bounded)
    "code",           # HTTP/RPC status classes
    "controller",     # controller names (static set)
    "endpoint",       # RPC route TEMPLATES (not URLs with ids)
    "event",          # staging/cache event kinds
    "instance_type",  # catalog-bounded
    "kind",           # decision/risk kinds (static set)
    "method",         # HTTP verbs
    "mode",           # encode/solve modes (static set)
    "outcome",        # ok | terminal | exhausted | deadline | ...
    "owner",          # pod owner KIND (ReplicaSet/Job/...), not owner name
    "phase",          # node/pod lifecycle phases
    "preemptor",      # preemption trigger classes
    "provisioner",    # provisioner names (operator-config-bounded)
    "reason",         # event/decision reasons (static set)
    "resource_type",  # cpu/memory/pods + accelerator extended resources
    "scraper",        # scraper names (static set)
    "service",        # RPC service names (static set)
    "site",           # tracemalloc top-site rank (bounded N)
    "slo",            # SLO objective names (settings-bounded)
    "source",         # cost-savings streams (spot/consolidation/...)
    "stage",          # lifecycle stage names (static set)
    "to",             # breaker target states (closed/open/half-open)
    "trigger",        # flight-recorder anomaly triggers (static set)
    "type",           # event types (Normal/Warning)
    "verdict",        # validation verdicts (static set)
    "window",         # SLO windows (fast/slow)
    "zone",           # catalog-bounded
}

#: identity-shaped keys that must never label a metric: each names a space
#: that grows with workload history, not with configuration
FORBIDDEN_LABEL_KEYS = {
    "pod", "pod_name", "name", "node", "node_name", "machine",
    "machine_name", "instance_id", "gang", "gang_name", "uid",
    "trace_id", "reconcile_id", "token",
}

#: the one exemption: fleet-state gauges keyed by live node, published via
#: replace_series full swaps + refresher pruning (series die with the node)
_NODE_NAME_EXEMPT_PREFIX = os.path.join("controllers", "metricsscraper") + os.sep
_EXEMPT_KEYS = {"node_name"}


def _label_dicts(call: ast.Call) -> List[ast.Dict]:
    """The candidate label-dict literals of one metric-mutator call."""
    out = []
    for arg in list(call.args) + [
        kw.value for kw in call.keywords if kw.arg == "labels"
    ]:
        if isinstance(arg, ast.Dict):
            out.append(arg)
    return out


def _is_metric_call(call: ast.Call) -> bool:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr in _METRIC_METHODS or fn.attr == "series_key"
    return isinstance(fn, ast.Name) and fn.id == "series_key"


def scan_file(path: str, rel: str) -> List[Tuple[str, int, str]]:
    """(rel_path, line, problem) for every offending label key in one file."""
    with open(path) as f:
        try:
            tree = ast.parse(f.read())
        except SyntaxError as e:
            return [(rel, e.lineno or 0, f"unparseable: {e.msg}")]
    problems = []
    exempt_file = rel.startswith(_NODE_NAME_EXEMPT_PREFIX)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_metric_call(node):
            continue
        for d in _label_dicts(node):
            for key_node in d.keys:
                if key_node is None:
                    continue  # a ** spread: checked at its own literal
                if not (
                    isinstance(key_node, ast.Constant)
                    and isinstance(key_node.value, str)
                ):
                    problems.append((
                        rel, key_node.lineno,
                        "computed label key (unreviewable cardinality)",
                    ))
                    continue
                key = key_node.value
                if key in _EXEMPT_KEYS and exempt_file:
                    continue
                if key in FORBIDDEN_LABEL_KEYS:
                    problems.append((
                        rel, key_node.lineno,
                        f"forbidden label key {key!r} (unbounded identity "
                        "space — roll it up or serve it on /debug/*)",
                    ))
                elif key not in ALLOWED_LABEL_KEYS:
                    problems.append((
                        rel, key_node.lineno,
                        f"label key {key!r} not in ALLOWED_LABEL_KEYS "
                        "(extend hack/check_metric_cardinality.py if the "
                        "key space is genuinely bounded)",
                    ))
    return problems


def check(package: str = PACKAGE) -> List[str]:
    """Every offense as a human-readable line; empty means clean."""
    problems: List[str] = []
    for root, dirs, files in os.walk(package):
        dirs[:] = [d for d in dirs if d != "__pycache__"]
        for name in sorted(files):
            if not name.endswith(".py"):
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, package)
            for rel_path, line, problem in scan_file(path, rel):
                problems.append(f"karpenter_tpu/{rel_path}:{line}: {problem}")
    return sorted(problems)


def main() -> int:
    problems = check()
    for p in problems:
        print(f"CARDINALITY: {p}", file=sys.stderr)
    if problems:
        return 1
    print(
        f"metric label keys bounded: {len(ALLOWED_LABEL_KEYS)} allowed keys, "
        f"{len(FORBIDDEN_LABEL_KEYS)} forbidden"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
