"""BENCH artifact writer — the robust version of the harness runner that
produced ``BENCH_r0x.json``.

The historical runner ran the bench, kept the last ~2000 bytes of COMBINED
stdout+stderr as ``tail``, and parsed the final line of that tail. Two ways
that breaks, both observed:

* the final line is huge (the seed-era detail line ran to tens of KB), so
  the stored tail starts mid-JSON and the "last line" is a fragment —
  ``BENCH_r03``–``r05`` all carry ``"parsed": null`` for exactly this;
* anything trailing the summary on the combined stream (XLA/absl teardown
  logs from a background compile thread, a late warning) becomes the last
  line, and it isn't JSON.

This writer fixes BOTH sides of the parse:

* **file channel** (preferred): when the command invokes ``bench.py``, a
  ``--summary-out <tmpfile>`` is appended automatically (or pass
  ``--summary-file`` to point at one the command writes itself). bench.py
  writes the summary JSON there atomically — no stdout scraping at all;
* **stdout fallback**: the FULL captured output is scanned backwards for
  the last line that strict-parses as a JSON object, preferring a line
  self-described with ``"summary": true`` (the contract bench.py's final
  line pins; see tests/test_bench_summary.py).

The tail stays a bounded byte window for humans; ``parsed`` no longer
depends on it.

Usage::

    python hack/bench_artifact.py --out BENCH_r06.json [--n 6] [--cmd '...']

The round-trip contract is pinned by tests/test_bench_summary.py.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import Optional, Tuple

TAIL_BYTES = 2000
DEFAULT_CMD = "if [ -f bench.py ]; then python bench.py; else exit 0; fi"


def parse_summary(output: str) -> Tuple[Optional[dict], Optional[dict]]:
    """(summary, any_json): the last ``{"summary": true}`` object line in
    ``output``, and the last line that parses as a JSON object at all.
    Strict parsing — NaN/Infinity tokens disqualify a line, matching
    non-Python consumers of the artifact."""
    summary = any_json = None
    for line in reversed(output.splitlines()):
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            obj = json.loads(line, parse_constant=_reject_constant)
        except ValueError:
            continue
        if not isinstance(obj, dict):
            continue
        if any_json is None:
            any_json = obj
        if obj.get("summary") is True:
            summary = obj
            break
    return summary, any_json


def _reject_constant(name: str):
    raise ValueError(f"non-strict JSON constant {name}")


def read_summary_file(path: str) -> Optional[dict]:
    """The summary a ``--summary-out`` run wrote, or None (file missing,
    empty, torn, or not a strict-JSON object — the stdout fallback then
    owns the parse). Never raises: artifact writing must survive any file
    state a crashed bench leaves behind."""
    try:
        with open(path) as f:
            obj = json.loads(f.read(), parse_constant=_reject_constant)
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


def run_and_capture(cmd: str, timeout: Optional[float] = None) -> Tuple[int, str]:
    proc = subprocess.run(
        cmd, shell=True, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, errors="replace", timeout=timeout,
    )
    return proc.returncode, proc.stdout or ""


def build_artifact(
    n: int, cmd: str, rc: int, output: str,
    summary_file: Optional[str] = None,
) -> dict:
    """The artifact dict. ``summary_file`` (when given and parseable) is
    the preferred source for ``parsed``; stdout scanning is the fallback,
    so the artifact degrades exactly to the pre-file behavior when the
    bench predates ``--summary-out`` or died before writing."""
    from_file = read_summary_file(summary_file) if summary_file else None
    summary, any_json = parse_summary(output)
    return {
        "n": n,
        "cmd": cmd,
        "rc": rc,
        "tail": output[-TAIL_BYTES:],
        "parsed": (
            from_file if from_file is not None
            else summary if summary is not None
            else any_json
        ),
        "parsed_source": (
            "file" if from_file is not None
            else "stdout" if (summary is not None or any_json is not None)
            else None
        ),
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", required=True, help="artifact path (JSON)")
    ap.add_argument("--n", type=int, default=0, help="round number")
    ap.add_argument("--cmd", default=DEFAULT_CMD, help="bench command")
    ap.add_argument(
        "--summary-file", default=None,
        help="read the summary from this file (written by the command, "
             "e.g. via bench.py --summary-out) instead of auto-injecting "
             "a temp file",
    )
    ap.add_argument("--timeout", type=float, default=None)
    args = ap.parse_args()
    cmd = args.cmd
    summary_file = args.summary_file
    cleanup = None
    if summary_file is None and "python bench.py" in cmd:
        # inject the file channel: every `python bench.py` invocation in
        # the command gains --summary-out to a temp path this process then
        # prefers (the narrower `python `-prefixed match keeps shell tests
        # like DEFAULT_CMD's `[ -f bench.py ]` intact)
        fd, summary_file = tempfile.mkstemp(suffix=".bench-summary.json")
        os.close(fd)
        os.unlink(summary_file)  # bench.py writes it atomically (or not at all)
        cleanup = summary_file
        cmd = cmd.replace(
            "python bench.py",
            f"python bench.py --summary-out {summary_file}",
        )
    try:
        rc, output = run_and_capture(cmd, timeout=args.timeout)
        artifact = build_artifact(
            args.n, args.cmd, rc, output, summary_file=summary_file
        )
    finally:
        if cleanup is not None:
            try:
                os.unlink(cleanup)
            except OSError:
                pass
    with open(args.out, "w") as f:
        json.dump(artifact, f)
        f.write("\n")
    ok = artifact["parsed"] is not None
    print(
        f"wrote {args.out} (rc={rc}, parsed="
        f"{artifact['parsed_source'] or 'null'})",
        file=sys.stderr,
    )
    return 0 if rc == 0 and ok else 1


if __name__ == "__main__":
    sys.exit(main())
