"""Metrics/doc drift gate — ``hack/docs`` verification for the metric catalog.

Checks, in BOTH directions, that the metric catalog in
``karpenter_tpu/utils/metrics.py`` and the generated reference
``docs/metrics.md`` agree:

* every cataloged metric has a non-empty HELP string (a bare name on
  ``/metrics`` is useless to an operator reading the exposition);
* every cataloged metric has a row in ``docs/metrics.md``;
* every row in ``docs/metrics.md`` names a metric that still exists (a
  deleted metric must take its doc row with it).

Wired as a tier-1 test (``tests/test_metrics_docs.py``) so drift fails CI,
and runnable standalone::

    python hack/check_metrics_docs.py   # exits 1 and prints the drift
"""

from __future__ import annotations

import os
import re
import sys
from typing import Dict, List

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
DOC = os.path.join(ROOT, "docs", "metrics.md")

_ROW = re.compile(r"^\|\s*`([a-zA-Z0-9_:]+)`\s*\|")


def cataloged_metrics() -> Dict[str, str]:
    """{metric name: help} for every Counter/Gauge/Histogram in the catalog
    module (the same scan hack/gen_docs.py renders the reference from)."""
    from karpenter_tpu.utils import metrics as m

    out: Dict[str, str] = {}
    for attr in dir(m):
        obj = getattr(m, attr)
        if type(obj).__name__ in ("Counter", "Gauge", "Histogram"):
            out[obj.name] = getattr(obj, "help", "") or ""
    return out


def documented_metrics(path: str = DOC) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [m.group(1) for line in f if (m := _ROW.match(line.strip()))]


def check() -> List[str]:
    """Every drift problem as a human-readable line; empty means clean."""
    catalog = cataloged_metrics()
    documented = documented_metrics()
    problems = []
    for name, help_text in sorted(catalog.items()):
        if not help_text.strip():
            problems.append(f"metric {name} has no HELP string")
        if name not in documented:
            problems.append(
                f"metric {name} missing from docs/metrics.md "
                "(run python hack/gen_docs.py)"
            )
    for name in documented:
        if name not in catalog:
            problems.append(
                f"docs/metrics.md documents {name} which no longer exists "
                "(run python hack/gen_docs.py)"
            )
    return problems


def main() -> int:
    problems = check()
    for p in problems:
        print(f"DRIFT: {p}", file=sys.stderr)
    if problems:
        return 1
    print(f"metrics docs current: {len(cataloged_metrics())} metrics checked")
    return 0


if __name__ == "__main__":
    sys.exit(main())
